"""Worker process skeleton + the controller's control panel.

Parity with reference ``realhf/system/worker_base.py``: a Worker runs a
poll loop, obeys configure/start/pause/exit commands, and publishes its
status through name_resolve; the controller's WorkerControlPanel issues
group commands over per-worker ZMQ REQ/REP sockets and monitors
statuses for failure detection (reference controller ``wait:275``).
"""

import dataclasses
import enum
import os
import pickle
import signal
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import zmq

from realhf_tpu import obs
from realhf_tpu.base import cluster, logging, name_resolve, names, network
from realhf_tpu.obs import flight, metrics, tracing

logger = logging.getLogger("worker_base")

#: Heartbeat cadence knobs. Workers read the env (the launcher exports
#: the experiment's FaultToleranceConfig values before spawning); the
#: TTL handed to TTL-capable name_resolve backends (redis) is a
#: multiple of the interval so one missed beat never expires an entry.
HEARTBEAT_INTERVAL_ENV = "REALHF_TPU_HEARTBEAT_INTERVAL"
DEFAULT_HEARTBEAT_INTERVAL = 2.0
HEARTBEAT_TTL_FACTOR = 5.0

#: Preemption-notice knobs. A preempted worker (cluster SIGTERM,
#: SIGUSR1, injected `preempt` fault, or `preempt` control command)
#: publishes a notice under ``names.worker_preempt``, runs its
#: ``_preempt_hook`` (emergency checkpoint / serving drain), keeps
#: serving in-flight work for the grace window, then exits with
#: status PREEMPTED. SIGTERM handling is opt-in via
#: ``REALHF_TPU_PREEMPT_SIGTERM=1`` -- schedulers that SIGTERM for
#: plain teardown must keep getting prompt exits.
PREEMPT_GRACE_ENV = "REALHF_TPU_PREEMPT_GRACE"
PREEMPT_SIGTERM_ENV = "REALHF_TPU_PREEMPT_SIGTERM"
DEFAULT_PREEMPT_GRACE = 15.0


class WorkerServerStatus(str, enum.Enum):
    READY = "READY"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    COMPLETED = "COMPLETED"
    ERROR = "ERROR"
    LOST = "LOST"
    # preemption notice received: draining within the grace window
    # (while alive), terminal after the graceful exit. Accounted-for
    # in liveness terms -- never LOST.
    PREEMPTED = "PREEMPTED"


@dataclasses.dataclass
class PollResult:
    sample_count: int = 0
    batch_count: int = 0


class WorkerServer:
    """Per-worker command endpoint (REP socket registered in
    name_resolve; reference WorkerServer:77)."""

    def __init__(self, experiment_name: str, trial_name: str,
                 worker_name: str,
                 heartbeat_interval: Optional[float] = None):
        self.worker_name = worker_name
        self._exp, self._trial = experiment_name, trial_name
        ctx = zmq.Context.instance()
        self._sock = ctx.socket(zmq.REP)
        port = self._sock.bind_to_random_port("tcp://*")
        host = network.gethostip()
        name_resolve.add(
            names.worker_key(experiment_name, trial_name, worker_name),
            f"tcp://{host}:{port}", replace=True)
        #: last published status (the /healthz surface reads it
        #: without a name_resolve round-trip)
        self.status: Optional[WorkerServerStatus] = None
        # host failure domain (system/pod.py): a pod launch injects
        # REALHF_TPU_HOST_ID per host; republish it so the master-side
        # watchdog can attribute whole-host losses as ONE HOST_LOST
        self.host_id = cluster.current_host_id()
        if self.host_id:
            name_resolve.add(
                names.worker_host(experiment_name, trial_name,
                                  worker_name),
                self.host_id, replace=True, delete_on_exit=False)
        self.set_status(WorkerServerStatus.READY)
        # liveness beacon: a daemon thread re-publishes a wall-clock
        # timestamp so the controller-side watchdog (system/watchdog.py)
        # can attribute silence to a dead/hung worker. A thread (not
        # the poll loop) keeps beating through long jit compiles and
        # multi-minute MFC executions.
        if heartbeat_interval is None:
            heartbeat_interval = float(os.environ.get(
                HEARTBEAT_INTERVAL_ENV, DEFAULT_HEARTBEAT_INTERVAL))
        self._hb_interval = heartbeat_interval
        # incarnation fencing: every beat carries this process's boot
        # id. A worker that dies and is relaunched FASTER than the
        # watchdog's staleness timeout would otherwise be a silent
        # message blackhole -- in-flight PUB'd requests died with the
        # old process, yet the fresh beat hides the death. The
        # watchdog treats a boot-id change as a loss edge
        # (system/watchdog.py) so the master requeues and re-routes.
        self.boot_id = uuid.uuid4().hex[:12]
        self._hb_key = names.worker_heartbeat(experiment_name, trial_name,
                                              worker_name)
        self._preempt_key = names.worker_preempt(
            experiment_name, trial_name, worker_name)
        # a RELAUNCHED worker must not inherit its previous
        # incarnation's preemption notice -- the master reads notice
        # presence as "this worker is retiring"
        self.clear_preempt_notice()
        self._hb_stop = threading.Event()
        # extra per-beat callbacks (e.g. a rollout server's fleet
        # lease renewal, serving/fleet.py): liveness signals that must
        # keep beating while the poll loop is stuck in a long jit
        # compile or a multi-minute MFC execution ride the SAME
        # beacon thread as the heartbeat
        self._beat_hooks = []
        self.beat()  # visible before the first interval elapses
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"heartbeat[{worker_name}]", daemon=True)
        self._hb_thread.start()

    def add_beat_hook(self, fn):
        """Invoke ``fn()`` on every heartbeat (beacon thread!). The
        hook must be thread-safe and non-blocking; exceptions are
        swallowed (the next beat retries)."""
        self._beat_hooks.append(fn)

    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the last beat this process published (the
        /healthz liveness figure; None before the first beat)."""
        last = getattr(self, "_last_beat_at", None)
        return None if last is None else time.monotonic() - last

    def beat(self):
        """Publish one heartbeat: ``"<wall-ts>:<boot-id>"`` (wall
        clock, not monotonic: the watchdog lives in another process;
        the boot id fences incarnations)."""
        self._last_beat_at = time.monotonic()
        try:
            name_resolve.add(
                self._hb_key, f"{time.time():.3f}:{self.boot_id}",
                replace=True, delete_on_exit=False,
                keepalive_ttl=self._hb_interval * HEARTBEAT_TTL_FACTOR)
        except Exception as e:  # noqa: BLE001 - next beat retries
            logger.warning("Heartbeat publish failed for %s: %s",
                           self.worker_name, e)
        for hook in list(self._beat_hooks):
            try:
                hook()
            except Exception as e:  # noqa: BLE001 - next beat retries
                logger.warning("Beat hook %r failed for %s: %s",
                               getattr(hook, "__name__", hook),
                               self.worker_name, e)

    def _heartbeat_loop(self):
        while not self._hb_stop.wait(self._hb_interval):
            self.beat()

    def stop_heartbeat(self):
        """Stop the beacon (clean exit; terminal status takes over as
        the liveness signal)."""
        self._hb_stop.set()

    def publish_preempt_notice(self, grace: float):
        """Announce preemption: ``"<wall-ts>:<grace-secs>"`` under the
        worker's preempt key. The master reacts to the notice (elastic
        degrade + drain) BEFORE the heartbeat ever goes stale."""
        try:
            name_resolve.add(
                self._preempt_key, f"{time.time():.3f}:{grace:.3f}",
                replace=True, delete_on_exit=False)
        except Exception as e:  # noqa: BLE001 - notice is best-effort
            logger.warning("Preempt notice publish failed for %s: %s",
                           self.worker_name, e)

    def clear_preempt_notice(self):
        try:
            name_resolve.delete(self._preempt_key)
        except name_resolve.NameEntryNotFoundError:
            pass
        except Exception as e:  # noqa: BLE001 - best-effort cleanup
            logger.warning("Preempt notice clear failed for %s: %s",
                           self.worker_name, e)

    def set_status(self, status: WorkerServerStatus):
        self.status = status
        name_resolve.add(
            names.worker_status(self._exp, self._trial, self.worker_name),
            status.value, replace=True, delete_on_exit=False)

    def poll_command(self, timeout: float = 0.0):
        """Returns (command, kwargs) or None; caller must respond via
        the returned responder before polling again."""
        if not self._sock.poll(timeout * 1000):
            return None
        cmd, kwargs = pickle.loads(self._sock.recv())
        return cmd, kwargs

    def respond(self, data: Any = None):
        self._sock.send(pickle.dumps(data))


class WorkerControlPanel:
    """Controller side: group commands + status monitoring
    (reference WorkerControlPanel:217)."""

    def __init__(self, experiment_name: str, trial_name: str):
        self._exp, self._trial = experiment_name, trial_name
        self._ctx = zmq.Context.instance()
        self._socks: Dict[str, zmq.Socket] = {}

    def connect(self, worker_names: List[str], timeout: float = 120.0):
        for w in worker_names:
            addr = name_resolve.wait(
                names.worker_key(self._exp, self._trial, w), timeout=timeout)
            s = self._ctx.socket(zmq.REQ)
            try:
                s.connect(addr)
            except BaseException:
                # a bad resolved address must not leak the socket
                # (graft-lint lifecycle-leak-on-raise)
                s.close(0)
                raise
            self._socks[w] = s

    def group_request(self, command: str,
                      worker_names: Optional[List[str]] = None,
                      kwargs: Optional[Dict] = None,
                      timeout: float = 600.0) -> Dict[str, Any]:
        targets = worker_names or list(self._socks)
        return self.group_request_varied(
            command, {w: kwargs or {} for w in targets}, timeout=timeout)

    def group_request_varied(self, command: str,
                             kwargs_by_worker: Dict[str, Dict],
                             timeout: float = 600.0) -> Dict[str, Any]:
        """group_request with per-worker kwargs. All requests go out
        before any reply is awaited, so command handlers that form a
        cross-worker barrier (e.g. configure joining a jax.distributed
        world) complete even when each worker needs different kwargs.

        Failure-aware: a worker whose handler raised replies the
        exception -- re-raised here with attribution -- and a worker
        that DIED mid-command (status ERROR) fails the wait promptly
        instead of hanging out the full timeout."""
        for w, kw in kwargs_by_worker.items():
            self._socks[w].send(pickle.dumps((command, kw or {})))
        out = {}
        for w in kwargs_by_worker:
            deadline = time.monotonic() + timeout
            while not self._socks[w].poll(1000):
                if self.get_worker_status(w) == WorkerServerStatus.ERROR:
                    raise RuntimeError(
                        f"Worker {w} died (status ERROR) during "
                        f"`{command}`.")
                if time.monotonic() > deadline:
                    raise TimeoutError(f"Worker {w} did not respond "
                                       f"to `{command}`.")
            out[w] = pickle.loads(self._socks[w].recv())
            if isinstance(out[w], Exception):
                raise RuntimeError(
                    f"Worker {w} failed handling `{command}`: "
                    f"{out[w]!r}") from out[w]
        return out

    def get_worker_status(self, worker_name: str) -> WorkerServerStatus:
        try:
            return WorkerServerStatus(name_resolve.get(
                names.worker_status(self._exp, self._trial, worker_name)))
        except name_resolve.NameEntryNotFoundError:
            return WorkerServerStatus.LOST

    def all_statuses(self, worker_names: List[str]
                     ) -> Dict[str, WorkerServerStatus]:
        return {w: self.get_worker_status(w) for w in worker_names}


class Worker:
    """Poll-loop worker (reference Worker:468). Subclasses implement
    `_configure(config)` and `_poll() -> PollResult`; `run()` drives the
    state machine until exit."""

    def __init__(self, experiment_name: str, trial_name: str,
                 worker_name: str):
        self.worker_name = worker_name
        # observability (realhf_tpu/obs/): label this process's
        # tracer/metrics/flight recorder; REALHF_TPU_TRACE=1 turns on
        # span export for every worker type uniformly
        obs.configure_from_env(worker_name, experiment=experiment_name,
                               trial=trial_name)
        self.server = WorkerServer(experiment_name, trial_name, worker_name)
        self._running = False
        self._exiting = False
        self.config = None
        # preemption state machine: a signal handler only flips
        # _preempt_signaled (async-signal-safe); the run loop converts
        # it into a published notice + hook + graceful deadline.
        self._preempt_signaled = False
        self._preempt_deadline: Optional[float] = None
        self._preempt_grace: Optional[float] = None
        self._preempt_hook_ran = False
        # live HTTP telemetry endpoints (obs/http.py): /metrics,
        # /healthz, /flight, /statusz on an ephemeral port, published
        # under names.telemetry so the pod controller resolves real
        # per-worker Prometheus scrape targets (started LAST: the
        # health provider reads the state initialized above). Opt-out:
        # REALHF_TPU_TELEMETRY=0. Never fatal.
        from realhf_tpu.obs import http as obs_http
        self.telemetry = obs_http.start_from_env(
            worker_name, health=self._telemetry_health)
        if self.telemetry is not None:
            try:
                name_resolve.add(
                    names.telemetry(experiment_name, trial_name,
                                    worker_name),
                    self.telemetry.address, replace=True)
            except Exception as e:  # noqa: BLE001 - scrape discovery
                # is advisory; the endpoints still answer directly
                logger.warning("Telemetry publish failed for %s: %s",
                               worker_name, e)

    # -- subclass API ---------------------------------------------------
    def _configure(self, config: Any):
        raise NotImplementedError()

    def _poll(self) -> PollResult:
        raise NotImplementedError()

    def _exit_hook(self):
        """Last-chance cleanup/checkpoint on exit (reference
        model_worker.py:953 recover save)."""

    def _preempt_hook(self, grace: float):
        """Emergency work on a preemption notice, run ONCE from the
        poll loop (never the signal handler) with ``grace`` seconds
        left: model workers emergency-save a durable checkpoint,
        serving workers drain (docs/serving.md)."""

    def _health_extra(self) -> Dict:
        """Subclass hook: extra /healthz fields. A truthy
        ``draining`` key flips the reported state to DRAINING (-> HTTP
        503) while the worker is otherwise RUNNING, so probers stop
        sending traffic the moment a serving drain starts."""
        return {}

    def _telemetry_health(self) -> Dict:
        """The /healthz payload (obs/http.py): worker status,
        heartbeat age, incarnation/host identity, plus whatever the
        subclass adds (lease/epoch state for serving workers)."""
        status = self.server.status
        state = status.value if status is not None else "UNKNOWN"
        if self.preempted:
            state = WorkerServerStatus.PREEMPTED.value
        try:
            extra = dict(self._health_extra() or {})
        except Exception as e:  # noqa: BLE001 - a subclass bug must
            # degrade the answer, not kill the endpoint
            extra = dict(health_extra_error=repr(e))
        if extra.pop("draining", False) and state == "RUNNING":
            state = "DRAINING"
        return dict(
            worker=self.worker_name, state=state,
            status=status.value if status is not None else None,
            running=self._running,
            preempted=self.preempted,
            heartbeat_age_secs=self.server.heartbeat_age(),
            boot_id=self.server.boot_id,
            host_id=self.server.host_id, **extra)

    # -- preemption -----------------------------------------------------
    @property
    def preempted(self) -> bool:
        return self._preempt_deadline is not None

    def notice_preemption(self, grace: Optional[float] = None,
                          reason: str = "signal"):
        """Enter the preemption grace window: publish the notice and
        status PREEMPTED (the master stops dispatching new work here
        and starts elastic degradation), keep serving in-flight work,
        and exit gracefully when the window closes. Idempotent."""
        if self._preempt_deadline is not None:
            return
        if grace is None:
            grace = float(os.environ.get(PREEMPT_GRACE_ENV,
                                         DEFAULT_PREEMPT_GRACE))
        grace = max(0.0, float(grace))
        self._preempt_grace = grace
        self._preempt_deadline = time.monotonic() + grace
        logger.warning(
            "Worker %s PREEMPTED (%s): %.1fs grace window; draining.",
            self.worker_name, reason, grace)
        self.server.publish_preempt_notice(grace)
        self.server.set_status(WorkerServerStatus.PREEMPTED)
        # postmortem trail: record AND dump now -- the process may be
        # SIGKILLed before the grace window closes
        flight.record("preempted", reason=reason, grace=grace)
        metrics.inc("worker_preempted_total")
        flight.dump(reason=f"preempted ({reason})")

    def _install_signal_handlers(self):
        """SIGUSR1 always means preemption notice; SIGTERM only when
        ``REALHF_TPU_PREEMPT_SIGTERM=1`` (schedulers that terminate
        with SIGTERM for teardown must keep prompt exits)."""

        def _handler(signum, _frame):
            # flag only -- the run loop publishes the notice (file IO
            # in a signal handler could reenter mid-operation)
            self._preempt_signaled = True

        try:
            signal.signal(signal.SIGUSR1, _handler)
            if os.environ.get(PREEMPT_SIGTERM_ENV) == "1":
                signal.signal(signal.SIGTERM, _handler)
        except ValueError:
            # not the main thread (in-process test harness): the
            # command/fault paths still deliver notices
            pass

    def _step_preemption(self) -> bool:
        """Advance the preemption state machine once per loop
        iteration; True when the grace window has closed and the
        worker should exit."""
        if self._preempt_signaled and self._preempt_deadline is None:
            self.notice_preemption(reason="signal")
        if self._preempt_deadline is None:
            return False
        if not self._preempt_hook_ran:
            self._preempt_hook_ran = True
            try:
                self._preempt_hook(max(
                    0.0, self._preempt_deadline - time.monotonic()))
            except Exception:  # noqa: BLE001 - still exit PREEMPTED
                logger.error("Preempt hook of %s failed.",
                             self.worker_name, exc_info=True)
        return time.monotonic() >= self._preempt_deadline

    # -------------------------------------------------------------------
    def _handle_command(self, cmd: str, kwargs: Dict) -> Any:
        if cmd == "configure":
            self.config = kwargs.get("config")
            result = self._configure(self.config)
            self.server.set_status(WorkerServerStatus.READY)
            return result
        if cmd == "start":
            self._running = True
            self.server.set_status(WorkerServerStatus.RUNNING)
            return "ok"
        if cmd == "pause":
            self._running = False
            self.server.set_status(WorkerServerStatus.PAUSED)
            return "ok"
        if cmd == "exit":
            self._exiting = True
            return "ok"
        if cmd == "ping":
            return "pong"
        if cmd == "preempt":
            # controller-initiated preemption drill (tests / manual
            # degrade rehearsals): same path as a cluster signal
            self.notice_preemption(grace=(kwargs or {}).get("grace"),
                                   reason="command")
            return "ok"
        if cmd == "metrics":
            # the worker health surface's metrics export
            # (docs/observability.md): Prometheus text + raw snapshot
            return dict(
                prometheus=metrics.to_prometheus(),
                snapshot=metrics.snapshot(),
                flight_events=len(flight.default_recorder()))
        if cmd == "profiler":
            # jax.profiler start/stop on THIS process (the master
            # overrides this to broadcast to its model workers)
            return self._handle_profiler(**(kwargs or {}))
        raise ValueError(f"Unknown worker command {cmd}")

    def _handle_profiler(self, action: str = "start",
                         path: Optional[str] = None) -> Dict:
        """Toggle a jax.profiler trace in this process; dumps land in
        ``{run_log_path}/trace/jax`` (TensorBoard/Perfetto-readable)
        unless ``path`` overrides."""
        import jax

        from realhf_tpu.base import monitor
        if action == "start":
            target = path or monitor.trace_dir("jax")
            try:
                jax.profiler.start_trace(target)
            except RuntimeError as e:  # already running
                return dict(ok=False, error=str(e))
            flight.record("profiler_start", path=target)
            return dict(ok=True, path=target)
        if action == "stop":
            try:
                jax.profiler.stop_trace()
            except RuntimeError as e:  # not running
                return dict(ok=False, error=str(e))
            flight.record("profiler_stop")
            return dict(ok=True)
        raise ValueError(f"Unknown profiler action {action!r}")

    def run(self):
        logger.info("Worker %s starting poll loop.", self.worker_name)
        self._install_signal_handlers()
        try:
            while not self._exiting:
                cmd = self.server.poll_command(
                    timeout=0.05 if not self._running else 0.0)
                if cmd is not None:
                    try:
                        self.server.respond(self._handle_command(*cmd))
                    except Exception as e:  # noqa: BLE001
                        self.server.respond(e)
                        raise
                if self._step_preemption():
                    logger.warning(
                        "Worker %s: preemption grace window closed; "
                        "exiting PREEMPTED.", self.worker_name)
                    break
                if self._running:
                    self._poll()
                # periodic observability housekeeping: metrics JSONL
                # snapshot + span-buffer flush (both cheap no-ops when
                # no sink/trace file is configured)
                metrics.maybe_flush()
                tracing.flush()
            self._exit_hook()
            tracing.flush()
            # final snapshot: maybe_flush is interval-gated, so a
            # short-lived worker would exit with its last gauge
            # values never persisted
            metrics.flush_final()
            self.server.stop_heartbeat()
            self.server.set_status(
                WorkerServerStatus.PREEMPTED if self.preempted
                else WorkerServerStatus.COMPLETED)
            if self.telemetry is not None:
                self.telemetry.stop()
        except Exception as e:
            # terminal status (not the beacon) is the liveness signal
            # from here on; the watchdog treats ERROR/COMPLETED as
            # "accounted for", never LOST. The flight recorder dumps
            # FIRST: the ring of recent events is the postmortem.
            flight.dump(reason=f"worker ERROR exit: {e!r}")
            tracing.flush()
            metrics.flush_final()
            self.server.stop_heartbeat()
            self.server.set_status(WorkerServerStatus.ERROR)
            raise
