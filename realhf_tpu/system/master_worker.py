"""Master worker: drives the dataflow graph across model workers.

TPU-native counterpart of reference ``realhf/system/master_worker.py``
(MasterWorker:841). The reference runs one asyncio coroutine per MFC
against an AsyncIOSequenceBuffer; here the same dataflow is an explicit
event-driven state machine stepped from ``_poll``: dispatch data
fetches, ASSEMBLE each MFC's next batch from whichever ready samples
exist (per-sample buffer granularity -- an assembly may span dataset
batches, so training drains trajectories the moment they are ready
instead of waiting for a full batch to complete every upstream key),
poll replies, advance per-sample consumption watermarks, account
epochs/steps on batch retirement, trigger save/eval, and record
recover info. MFCs of the same or consecutive steps whose models live
on different workers execute CONCURRENTLY -- the decoupled allocation
concurrency that is the reference's core throughput claim.

Off-policyness guard (reference master_worker.py:503-509), restated on
watermarks: an MFC of a trainable role may claim samples only up to
``trained + (1 + max_head_offpolicyness) * n_seqs`` where ``trained``
is the role's train-MFC consumption watermark -- with uniform n_seqs
this reduces exactly to "batch k dispatches once the train MFCs
completed batch k-1-max_head_offpolicyness".
"""

import pickle
import time
from typing import Dict

from realhf_tpu.api.config import ModelInterfaceType
from realhf_tpu.api.dfg import DFG
from realhf_tpu.api.experiment import FaultToleranceConfig
from realhf_tpu.base import (
    constants,
    logging,
    name_resolve,
    names,
    recover,
    timeutil,
)
from realhf_tpu.base.retry import RetryPolicy, retry_call
from realhf_tpu.obs import flight, metrics, tracing
from realhf_tpu.system import worker_base
from realhf_tpu.system.buffer import SequenceBuffer
from realhf_tpu.system.request_reply_stream import NameResolvingRequestClient
from realhf_tpu.system.watchdog import (
    ExclusionBook,
    Watchdog,
    WorkerLostError,
)

logger = logging.getLogger("master_worker", "benchmark")


class MasterWorker(worker_base.Worker):
    """Config dict: {spec_path | spec, recover_mode}."""

    def _configure(self, config: Dict):
        spec = config.get("spec")
        if spec is None:
            with open(config["spec_path"], "rb") as f:
                spec = pickle.load(f)
        self.spec = spec
        constants.set_experiment_trial_names(spec.experiment_name,
                                             spec.trial_name)

        self.dfg = DFG(spec.mfcs)
        self.input_keys_of = {n.name: tuple(n.input_keys)
                              for n in self.dfg.nodes}
        # per-MFC batch size (api/dfg.MFCDef.n_seqs): each MFC drains
        # the buffer at its own granularity; assemblies may span
        # dataset batches
        self.n_seqs_of = {n.name: int(n.n_seqs) for n in self.dfg.nodes}
        producers = self.dfg.G.graph["data_producers"]
        self.producers_of = {
            n.name: tuple(sorted({producers[k].name
                                  for k in n.input_keys
                                  if k in producers}))
            for n in self.dfg.nodes}
        # data key -> producing MFC (host-loss output invalidation)
        self.key_producer = {k: p.name for k, p in producers.items()}
        # EXEC worker group per node: the role's group, or the MFC
        # allocation's own group (per-MFC device-subset placement).
        # Requests go to every member; the leader -- first in the
        # group -- replies with data, members ack.
        self.node_workers = {
            n.name: [f"model_worker/{w}"
                     for w in spec.workers_of_node(n.name, n.role)]
            for n in self.dfg.nodes}
        self.node_worker = {name: ws[0]
                            for name, ws in self.node_workers.items()}
        # Cross-group nodes: exec group != the role's primary group.
        # Their replicas are refreshed by a param sync the master
        # attaches to each dispatch once the role has trained
        # (reference _attach_payloads_with_hooks,
        # master_worker.py:296).
        self.cross_group_nodes = {
            n.name for n in self.dfg.nodes
            if spec.is_cross_group(n.name, n.role)}
        self.role_workers = {
            r: [f"model_worker/{w}" for w in spec.workers_of_role(r)]
            for r in spec.models}
        self.all_workers = sorted(
            {w for ws in self.node_workers.values() for w in ws}
            | {w for n in self.dfg.nodes
               for w in self.role_workers[n.role]
               if n.name in self.cross_group_nodes})
        src = self.dfg.sources[0]
        self.data_owner = self.node_worker[src.name]
        # roles with a train MFC -> that MFC name (staleness guard)
        self.train_nodes_of_role: Dict[str, list] = {}
        for n in self.dfg.nodes:
            if n.interface_type == ModelInterfaceType.TRAIN_STEP:
                self.train_nodes_of_role.setdefault(n.role, []).append(
                    n.name)

        self.buffer = SequenceBuffer(
            [n.name for n in self.dfg.nodes],
            capacity=max(1, spec.max_concurrent_batches),
            n_seqs_of=self.n_seqs_of,
            input_keys_of=self.input_keys_of,
            producers_of=self.producers_of)

        self.stream = NameResolvingRequestClient(
            spec.experiment_name, spec.trial_name)

        ctl = spec.ctl
        self.save_ctl = timeutil.EpochStepTimeFreqCtl(
            freq_epoch=ctl.save_freq_epochs, freq_step=ctl.save_freq_steps,
            freq_sec=ctl.save_freq_secs)
        self.eval_ctl = timeutil.EpochStepTimeFreqCtl(
            freq_epoch=ctl.eval_freq_epochs, freq_step=ctl.eval_freq_steps,
            freq_sec=None)

        self.recover_mode = config.get("recover_mode", "disabled")
        self.global_step = 0
        self._start_epoch = 0
        self._ids_to_skip = set()
        # role -> manifest path of the last committed durable
        # checkpoint (RecoverInfo v3, system/ckpt_manager.py)
        self._ckpt_manifests: Dict[str, str] = {}
        if self.recover_mode == "resume":
            # tolerant load: a corrupt/truncated/future-schema file
            # degrades to a fresh start, never a crash loop
            info = recover.load_safe()
            if info is not None:
                self.global_step = info.last_step_info.global_step
                self._start_epoch = info.recover_start.epoch
                self._ids_to_skip = set(info.hash_vals_to_ignore)
                self._ckpt_manifests = dict(
                    getattr(info, "ckpt_manifests", None) or {})
                if info.buffer_state:
                    # restore only the batch-id watermark: the
                    # in-flight entries' tensors died with the old
                    # workers, and their ids are absent from
                    # hash_vals_to_ignore so the data refetches
                    self.buffer.load_state_dict(dict(
                        info.buffer_state, entries=[], batches=[]))
                logger.info(
                    "Master resuming at global step %d (epoch %d, %d "
                    "consumed ids, %d batches were in flight, recover "
                    "schema v%d).", self.global_step, self._start_epoch,
                    len(self._ids_to_skip),
                    len((info.buffer_state or {}).get("entries", ())),
                    info.version)

        # fault tolerance: heartbeat watchdog over the worker fleet,
        # excluded-workers bookkeeping, per-MFC requeue accounting.
        # Host failure domains (system/pod.py): workers self-publish
        # their pod host id, and both the watchdog and the exclusion
        # book aggregate per host -- a preempted VM is ONE HOST_LOST
        # with one backoff entry, not N independent worker losses.
        self.ft = getattr(spec, "ft", None) or FaultToleranceConfig()
        from realhf_tpu.system.pod import name_resolve_host_lookup
        self._host_of = name_resolve_host_lookup(
            spec.experiment_name, spec.trial_name)
        self.watchdog = Watchdog(
            spec.experiment_name, spec.trial_name, self.all_workers,
            timeout=self.ft.heartbeat_timeout,
            grace=self.ft.startup_grace_secs,
            poll_interval=self.ft.watchdog_poll_secs,
            host_of=self._host_of,
            host_window=getattr(self.ft, "host_lost_window_secs", None))
        self._exclusions = ExclusionBook(
            base=self.ft.exclude_base_secs,
            max_delay=self.ft.exclude_max_secs,
            host_of=self._host_of)
        self._mfc_requeues: Dict[tuple, int] = {}  # (aid, mfc) -> count
        # (aid, mfc) -> (failed fetch plan, ts): dispatch cooldown
        # after a survivor reported fetch_failed for that exact plan
        self._fetch_failed: Dict[tuple, tuple] = {}
        self._fetch_requeues = 0
        # elastic degraded-mode training (system/elastic.py): re-plan
        # MFCs of preempted/LOST workers onto survivors; re-expand on
        # rejoin. "Retiring" workers (preempt notice seen, or lost
        # with their nodes migrated) are ineligible for dispatch but
        # exempt from the fatal-loss deadline once nothing needs them.
        self.elastic = None
        if getattr(self.ft, "elastic_degrade", False):
            from realhf_tpu.system.elastic import ElasticPlanner
            self.elastic = ElasticPlanner(
                self.spec, self.dfg,
                max_adopted_per_worker=getattr(
                    self.ft, "max_adopted_per_worker", 2))
        self._retiring: set = set()
        self._preempt_seen: set = set()

        # runtime state
        self._subscribed = False
        self._fetch_inflight = False
        # completed fetch_data replies THIS incarnation: the exact
        # number of dataloader advances a data-owner successor must
        # replay to take over mid-epoch (elastic handoff)
        self._fetches_done = 0
        # request_id -> (aid, mfc_name, worker, kind); kind in
        # {leader, member, fetch, clear, sync}
        self._inflight: Dict[str, tuple] = {}
        # assembly id -> primary dataset batch id (exec-log / span
        # anchoring; assemblies pop from the buffer on completion but
        # member replies can still arrive afterwards). Bounded sweep
        # keeps it from growing with the trial.
        self._aid_bid: Dict[int, int] = {}
        # per-MFC per-worker execution spans + peak HBM (reference
        # __log_gpu_stats table, model_worker.py:999-1094)
        self._exec_log: list = []
        self._logged_bids: set = set()
        self._exec_history: list = []
        self._consumed_ids = list(self._ids_to_skip)
        self._cur_epoch = self._start_epoch
        self._epochs_fetched = 0  # epoch boundary accounting
        self._done_fetching = False
        self._complete = False
        self._step_t0 = None
        self._step_stats: Dict[str, Dict] = {}
        # batch_id -> open step span (obs/tracing.py): the ancestor
        # every dispatch/worker/serving span of that batch nests under
        # in the merged Chrome trace. Opened on put_batch, finished
        # when the batch completes (or the master exits).
        self._step_spans: Dict[int, tracing.Span] = {}
        # On resume the live window starts at the restored batch-id
        # watermark (exec-log sweeping); the off-policyness guard runs
        # on this incarnation's consumption watermarks, which restart
        # at zero together -- no pre-crash batch can deadlock it.
        self._min_live_bid = min(self.buffer.batch_ids()
                                 + [self.buffer.next_batch_id])
        # cross-group param sync bookkeeping: how often each role has
        # trained, and the last version the primary group was asked to
        # publish (keyed per ROLE -- the blob is per-role, so N cross
        # nodes of one role share a single gather+publish per version)
        self._role_version: Dict[str, int] = {
            role: 0 for role in self.train_nodes_of_role}
        self._last_synced: Dict[str, int] = {}
        self._sync_nonce = 0
        return "master-configured"

    # ------------------------------------------------------------------
    def _publish_status(self, status: str):
        name_resolve.add(
            names.experiment_status(self.spec.experiment_name,
                                    self.spec.trial_name),
            status, replace=True, delete_on_exit=False)

    def _offpolicy_ok(self, asm) -> bool:
        """Watermark form of the reference off-policyness guard: an
        MFC of a trainable role may run ahead of the role's train
        MFCs by at most (1 + max_head_offpolicyness) of its own
        batches, measured in SAMPLES (per-MFC consumption
        watermarks)."""
        node = self.dfg.find(asm.mfc)
        train_nodes = self.train_nodes_of_role.get(node.role)
        if not train_nodes:
            return True
        trained = min(self.buffer.consumed(t) for t in train_nodes)
        budget = (1 + self.spec.max_head_offpolicyness) \
            * self.n_seqs_of[asm.mfc]
        return asm.end_mark <= trained + budget

    def _input_plan(self, aid: int) -> tuple:
        """The per-key/per-owner fetch plan a dispatch of this
        assembly would use right now (hashable, for fetch-failure
        staleness comparison)."""
        return tuple(sorted(
            (k, o, tuple(oids))
            for k, owners in self.buffer.assembly_plan(aid).items()
            for o, oids in owners.items()))

    def _dispatchable(self, asm) -> bool:
        mfc_name = asm.mfc
        if not self._workers_eligible(self.node_workers[mfc_name]):
            return False
        # an upstream invalidation may have revoked readiness between
        # assembly and dispatch (host loss): wait for the recompute
        if not self.buffer.assembly_ready(asm.aid):
            return False
        # input owners: never dispatch a fetch plan pointing at a
        # watchdog-LOST worker (the tensors died with it; invalidation
        # + recompute will re-home them). Retiring-but-draining owners
        # stay fetchable -- the preemption grace window exists exactly
        # so consumers can still pull from them.
        if self.buffer.plan_owners(asm.aid) \
                & set(self.watchdog.lost_workers()):
            return False
        failed = self._fetch_failed.get((asm.aid, mfc_name))
        if failed is not None:
            failed_plan, ts = failed
            cooldown = self.ft.heartbeat_timeout \
                + 2 * self.ft.watchdog_poll_secs
            if failed_plan == self._input_plan(asm.aid) \
                    and time.monotonic() - ts < cooldown:
                # same plan just failed; give the watchdog time to
                # attribute the owner's death before retrying
                return False
        return self._offpolicy_ok(asm)

    # -- fault tolerance -----------------------------------------------
    def _workers_eligible(self, workers) -> bool:
        """Dispatch gate: every addressed worker must be live, out of
        its exclusion window (a flapping worker is not re-picked until
        its backoff expires), and not retiring under a preemption
        notice."""
        return all(not self._exclusions.is_excluded(w)
                   and w not in self.watchdog.lost_workers()
                   and w not in self._retiring
                   for w in workers)

    def _active_workers(self) -> list:
        """Fan-out targets for best-effort broadcasts (cache clears):
        the fleet minus retiring workers, whose requests would pile up
        unanswered in ``_inflight`` forever."""
        return [w for w in self.all_workers if w not in self._retiring]

    def _check_liveness(self):
        """Run the watchdog (rate-limited); react to preemption
        notices (elastic degrade BEFORE the heartbeat goes stale);
        requeue or fail work attributed to newly lost workers; enforce
        the fatal deadline for workers that stay lost; re-expand when
        a degraded node's home worker rejoins."""
        notices = self.watchdog.preempt_notices()
        new_notices = sorted(w for w in notices
                             if w not in self._preempt_seen)
        if new_notices:
            # all co-preempted workers (a host preemption notices every
            # worker on the VM at once) are handled as ONE batch: the
            # whole dying set retires BEFORE any handoff/degrade
            # planning, so successors and adopters are chosen OFF the
            # dying host in one shot
            self._preempt_seen.update(new_notices)
            self._on_workers_preempted(new_notices, notices)
        lost_now = self.watchdog.poll()
        if lost_now:
            self._on_workers_lost(lost_now)
        fatal = self.watchdog.lost_longer_than(
            self.ft.worker_lost_fatal_secs)
        # a retired worker whose every responsibility was migrated is
        # no longer load-bearing: its continued absence must not fail
        # a trial that is training fine on the degraded plan
        fatal = [w for w in fatal if self._still_needed(w)]
        if fatal:
            # the WorkerLostError propagates to worker_base.run(),
            # whose ERROR exit path dumps the master's flight ring --
            # record the verdict context first so the dump names it
            flight.record("worker_lost_fatal", workers=fatal,
                          inflight=self._work_attributed_to(fatal))
            raise WorkerLostError(
                fatal, inflight=self._work_attributed_to(fatal),
                detail="Lost longer than worker_lost_fatal_secs="
                       f"{self.ft.worker_lost_fatal_secs:.0f}s; "
                       "failing the trial for relaunch-level recovery.")
        if self._retiring:
            self._maybe_reexpand()

    def _still_needed(self, worker: str) -> bool:
        """Does anything still route through ``worker``? Data
        ownership, any MFC's exec group, or sender duty for a
        cross-group param sync."""
        if worker == self.data_owner:
            return True
        if any(worker in ws for ws in self.node_workers.values()):
            return True
        for n in self.dfg.nodes:
            if n.name in self.cross_group_nodes \
                    and worker in self.role_workers.get(n.role, ()):
                return True
        return False

    def _work_attributed_to(self, workers) -> list:
        """MFC names in flight on, or queued for, any of ``workers``
        (for attributed error messages)."""
        ws = set(workers)
        out = {f"{mfc}@assembly{aid}"
               for aid, mfc, w, kind in self._inflight.values()
               if w in ws and mfc is not None}
        for bid in self.buffer.batch_ids():
            e = self.buffer.get(bid)
            for m in self._mfcs_pending(e):
                if ws & set(self.node_workers[m]):
                    out.add(f"{m}@batch{bid}")
        return sorted(out)

    def _mfcs_pending(self, entry) -> list:
        return [n.name for n in self.dfg.nodes
                if n.name not in entry.completed]

    def _on_worker_lost(self, worker: str):
        self._on_workers_lost([worker])

    def _on_workers_lost(self, workers):
        """Heartbeats expired (possibly a whole host at once): exclude
        with backoff (host-coalesced -- one VM loss is one backoff
        entry), drop in-flight requests, and requeue the affected MFCs
        (bounded by ft.max_mfc_retries) so a flap heals without
        failing the trial; exhausted retries raise a WorkerLostError
        naming the worker and the MFC. With elastic degradation on,
        the WHOLE dying set retires first, then every migratable MFC
        routed through it is re-planned onto survivors in one shot --
        completed outputs homed on the dead workers are invalidated
        (their tensors died with the host) so consumers recompute from
        the surviving data owner instead of fetching from a corpse."""
        workers = sorted(set(workers))
        if self.elastic is not None:
            self._retiring.update(workers)
        for w in workers:
            self._exclusions.exclude(w)
        # order matters: the doomed-consumer scan reads key_owner
        # before invalidation scrubs it
        self._requeue_doomed_consumers(set(workers))
        self._invalidate_lost_outputs(workers)
        for w in workers:
            self._drop_and_requeue(w)
        if self.elastic is not None:
            # plan over the FULL dead/retiring set, not just this
            # edge: host members can flip LOST across successive
            # polls, and an adoption that failed because its target
            # was a sibling casualty of the same host must be
            # re-planned now (nodes already migrated have re-routed
            # groups and are skipped automatically)
            self._elastic_degrade(
                set(workers) | self._retiring
                | set(self.watchdog.lost_workers()))

    def _on_worker_preempted(self, worker: str):
        self._on_workers_preempted(
            [worker], self.watchdog.preempt_notices())

    def _on_workers_preempted(self, workers, notices: Dict):
        """Preemption notices arrived (SIGTERM-equivalent, grace
        windows running) -- for a host preemption, one per worker on
        the VM, handled as a single batch: stop dispatching to the
        whole dying set, requeue what was in flight on it (it may
        still finish -- the duplicate reply drains harmlessly), hand
        data ownership OFF the dying set while its data server still
        answers, and migrate its MFCs while the old incarnations are
        still draining."""
        workers = sorted(set(workers))
        self._retiring.update(workers)
        by_host: Dict = {}
        for w in workers:
            by_host.setdefault(self._host_of(w) or w, []).append(w)
        for key, ws in sorted(by_host.items()):
            grace = max((notices.get(w, (0, 0))[1] for w in ws),
                        default=0.0)
            for w in ws:
                metrics.inc("master_preempt_notices_total", worker=w)
            if len(ws) > 1:
                flight.record("host_preempt_notice", host=key,
                              workers=ws, grace=grace)
            else:
                flight.record("preempt_notice", worker=ws[0],
                              grace=grace)
            logger.warning(
                "%s announced PREEMPTION (%.1fs grace): retiring "
                "from dispatch%s.",
                f"Host {key} ({ws})" if len(ws) > 1
                else f"Worker {ws[0]}", grace,
                "" if self.elastic is None
                else " and re-planning its MFCs onto survivors")
        if self.elastic is not None and self.data_owner in workers:
            # handoff FIRST: it must land while the draining worker's
            # data server still answers inside the grace window; the
            # whole dying set is already retiring, so the successor
            # scan lands off the dying host in one shot
            grace = max((notices.get(w, (0, 0))[1] for w in workers),
                        default=0.0)
            self._handoff_data_owner(self.data_owner, grace)
        for w in workers:
            self._drop_and_requeue(w)
        if self.elastic is not None:
            self._elastic_degrade(workers)

    def _requeue_doomed_consumers(self, ws):
        """An MFC in flight on a SURVIVOR whose input fetch plan
        points at a just-dead worker can only fail its data fetch:
        drop the dispatch and release the assembly (ready_assemblies
        re-offers it once the producer has recomputed the lost
        inputs)."""
        seen = set()
        for rid, (aid, mfc, w, kind) in list(self._inflight.items()):
            if kind != "leader" or mfc is None or w in ws:
                continue  # dead-worker rids are _drop_and_requeue's job
            if aid in seen or self.buffer.assembly(aid) is None:
                continue
            doomed = self.buffer.plan_owners(aid) & ws
            if not doomed:
                continue
            seen.add(aid)
            siblings = [r for r, ref in list(self._inflight.items())
                        if ref[0] == aid and ref[1] == mfc]
            for r in siblings:
                self._inflight.pop(r, None)
            self.stream.discard(siblings)
            self.buffer.release_assembly(aid)
            logger.warning(
                "Requeued in-flight MFC %s (assembly %d): its input "
                "fetch plan references dead worker(s) %s.", mfc, aid,
                sorted(doomed))

    def _on_mfc_fetch_failed(self, aid, mfc_name, worker, error):
        """A survivor could not assemble an MFC's inputs (their owner
        died without a grace window): drop the dispatch group and
        requeue, bounded by the same per-MFC retry budget as worker
        loss -- a persistent failure still fails the trial with
        attribution instead of looping forever."""
        siblings = [r for r, ref in list(self._inflight.items())
                    if ref[0] == aid and ref[1] == mfc_name]
        for r in siblings:
            self._inflight.pop(r, None)
        self.stream.discard(siblings)
        if self.buffer.assembly(aid) is not None:
            self._fetch_failed[(aid, mfc_name)] = (
                self._input_plan(aid), time.monotonic())
        n = self._mfc_requeues.get((aid, mfc_name), 0) + 1
        self._mfc_requeues[(aid, mfc_name)] = n
        # fetch failures get a wider budget than worker loss: the
        # first one typically races the watchdog's attribution of the
        # dead owner (the dispatch cooldown absorbs the gap)
        budget = max(3, self.ft.max_mfc_retries)
        if n > budget:
            flight.record("fetch_failed_fatal", mfc=mfc_name,
                          assembly=aid, worker=worker, error=error)
            raise WorkerLostError(
                worker, inflight=[f"{mfc_name}@assembly{aid}"],
                detail=f"MFC {mfc_name} (assembly {aid}) input fetch "
                       f"failed {n}x ({error}); giving up.")
        self.buffer.release_assembly(aid)
        metrics.inc("master_fetch_failed_requeues_total", mfc=mfc_name)
        logger.warning(
            "Requeued MFC %s (assembly %d): %s reported fetch_failed "
            "(%s; attempt %d/%d).", mfc_name, aid, worker, error, n,
            budget)

    def _invalidate_lost_outputs(self, workers):
        """Un-complete MFCs whose output tensors were homed on workers
        that died WITHOUT a grace window (SIGKILL / host loss): the
        data-plane pieces are gone, so any consumer dispatch would
        fail its fetch. Re-marking the producer undispatched makes it
        recompute -- on the adopter once elastic degrade reroutes it
        -- from inputs still homed on the surviving data owner. This
        recomputes, it never re-consumes: the batch's sample ids were
        drawn from the dataset exactly once."""
        ws = sorted(set(workers))
        for bid, mfc, keys in self.buffer.invalidate_worker_outputs(
                ws, self.key_producer):
            metrics.inc("master_outputs_invalidated_total", mfc=mfc)
            logger.warning(
                "Batch %d: %s outputs %s died with worker(s) %s; "
                "re-marked for recompute.", bid, mfc, keys, ws)

    def _handoff_data_owner(self, worker: str, grace: float):
        """The preempted worker owns the data plane (dataset loader +
        live batches' tensors): hand both to a survivor before the
        grace window closes. The successor pulls every live batch's
        pieces still homed on the draining worker (its data server
        keeps answering until the graceful exit), builds its own
        dataloader, and replays ``_fetches_done`` advances -- the
        seeded loader reproduces the exact stream, so position-based
        replay means no sample is re-consumed or skipped. On failure
        the old owner stays the owner and ``_still_needed`` keeps its
        fatal deadline armed (relaunch-level recovery)."""
        succ = next((w for w in self.all_workers
                     if w != worker and w not in self._retiring
                     and w not in self.watchdog.lost_workers()), None)
        if succ is None:
            logger.error("Data owner %s preempted but no survivor can "
                         "take over; relaunch-level recovery applies.",
                         worker)
            return
        rescue = self.buffer.rescue_plan(worker)
        payload = dict(from_worker=worker,
                       fetches_done=self._fetches_done,
                       rescue=rescue,
                       fetch_timeout=max(5.0, grace))
        try:
            rids = self.stream.request([succ], "adopt_data",
                                       datas=[payload])
            replies = self.stream.gather_replies(
                rids, timeout=self.ft.gather_timeout_secs,
                check_liveness=lambda: self.watchdog.raise_if_lost(
                    [succ], inflight=["adopt_data"]))
            err = next((p.data["error"] for p in replies
                        if isinstance(p.data, dict)
                        and p.data.get("error")), None)
            if err is not None:
                raise RuntimeError(f"successor rescue failed: {err}")
        except Exception as e:  # noqa: BLE001 - keep the old owner
            logger.error(
                "Data-owner handoff %s -> %s FAILED (%s); %s stays "
                "the owner and its loss is fatal after the deadline.",
                worker, succ, e, worker)
            return
        self.data_owner = succ
        self.buffer.rehome_owner(worker, succ)
        logger.warning(
            "DATA OWNERSHIP handed off %s -> %s: %d live batches "
            "rescued, loader replayed to fetch %d.", worker, succ,
            len(rescue), self._fetches_done)

    def _drop_and_requeue(self, worker: str):
        lost_refs = [(rid, ref) for rid, ref in self._inflight.items()
                     if ref[2] == worker]
        for rid, (aid, mfc_name, _w, kind) in lost_refs:
            self._inflight.pop(rid, None)
            self.stream.discard([rid])
            if kind in ("leader", "member"):
                # drop the sibling requests of the same dispatch too:
                # surviving members' late replies fall through the
                # unknown-rid path harmlessly, and the whole MFC
                # re-dispatches as one group
                siblings = [r for r, ref in list(self._inflight.items())
                            if ref[0] == aid and ref[1] == mfc_name]
                for r in siblings:
                    self._inflight.pop(r, None)
                self.stream.discard(siblings)
                n = self._mfc_requeues.get((aid, mfc_name), 0) + 1
                self._mfc_requeues[(aid, mfc_name)] = n
                if n > self.ft.max_mfc_retries:
                    flight.record("worker_lost_fatal", worker=worker,
                                  mfc=mfc_name, assembly=aid,
                                  requeues=n - 1)
                    raise WorkerLostError(
                        worker, inflight=[f"{mfc_name}@assembly{aid}"],
                        detail=f"MFC {mfc_name} (assembly {aid}) "
                               f"already requeued {n - 1}x; giving up.")
                self.buffer.release_assembly(aid)
                logger.warning(
                    "Requeued MFC %s (assembly %d) after losing "
                    "worker %s (attempt %d/%d).", mfc_name, aid,
                    worker, n, self.ft.max_mfc_retries)
            elif kind == "fetch":
                self._fetch_requeues += 1
                if self._fetch_requeues > self.ft.max_mfc_retries:
                    flight.record("worker_lost_fatal", worker=worker,
                                  handle="fetch_data",
                                  requeues=self._fetch_requeues - 1)
                    raise WorkerLostError(
                        worker, inflight=["fetch_data"],
                        detail="Data owner lost; fetch already "
                               f"requeued {self._fetch_requeues - 1}x.")
                self._fetch_inflight = False
                logger.warning("Requeued fetch_data after losing data "
                               "owner %s.", worker)
            else:  # clear / sync / adopt / release: drop silently
                logger.warning("Dropped in-flight %s request to lost "
                               "worker %s.", kind, worker)

    # -- elastic degrade / re-expand (system/elastic.py) ----------------
    def _alive_worker_indices(self) -> list:
        out = []
        for w in self.all_workers:
            if w in self._retiring or w in self.watchdog.lost_workers():
                continue
            out.append(int(w.rsplit("/", 1)[1]))
        return sorted(out)

    def _elastic_degrade(self, workers):
        """Re-plan every MFC currently routed through the lost/dying
        ``workers`` (one worker, or a whole host's worth in ONE shot:
        adopters are chosen with the full dying set excluded, so no
        plan ever lands on a sibling casualty of the same VM) onto
        survivors: each adopter builds a replica engine on a degraded
        layout and weights reshard onto it (live primary / verified
        emergency checkpoint / deterministic seed + param-sync
        refresh). Non-migratable nodes (train steps, hit primaries)
        keep the existing requeue/fatal semantics."""
        if isinstance(workers, str):
            workers = [workers]
        workers = sorted(set(workers))
        lost_idx = {int(w.rsplit("/", 1)[1]) for w in workers
                    if w.startswith("model_worker/")}
        alive = self._alive_worker_indices()
        for node in self.dfg.nodes:
            group = self.node_workers[node.name]
            if not set(group) & set(workers):
                continue
            plan = self.elastic.plan_degraded(node.name, lost=lost_idx,
                                              alive=alive)
            if plan is None:
                continue
            new_workers = [f"model_worker/{i}" for i in plan.workers]
            data = dict(node=node.name, parallel=plan.parallel,
                        cross_group=plan.cross_group, try_ckpt=True)
            try:
                rids = self.stream.request(
                    new_workers, "adopt_node",
                    datas=[data] * len(new_workers))
                replies = self.stream.gather_replies(
                    rids, timeout=self.ft.gather_timeout_secs,
                    check_liveness=lambda: self.watchdog.raise_if_lost(
                        new_workers,
                        inflight=[f"adopt_node:{node.name}"]))
            except Exception as e:  # noqa: BLE001 - degrade is best
                # effort: the node stays routed to the dead worker and
                # the ordinary requeue/fatal machinery takes over
                logger.error(
                    "Elastic adoption of %s by %s FAILED (%s); "
                    "falling back to requeue/fatal handling.",
                    node.name, new_workers, e)
                continue
            self.elastic.record_degraded(
                plan, original_workers=list(group),
                original_cross_group=node.name in self.cross_group_nodes)
            self.node_workers[node.name] = new_workers
            self.node_worker[node.name] = new_workers[0]
            if plan.cross_group:
                self.cross_group_nodes.add(node.name)
            else:
                self.cross_group_nodes.discard(node.name)
            metrics.inc("elastic_degrade_total", node=node.name)
            flight.record("elastic_degrade", node=node.name,
                          lost_workers=workers, adopters=new_workers)
            logger.warning(
                "DEGRADED %s: %s -> %s on layout %s (%s); installed "
                "weight version %s. Training continues at reduced "
                "throughput.", node.name, group, new_workers,
                plan.parallel, plan.reason,
                [p.data.get("version") if isinstance(p.data, dict)
                 else "?" for p in replies])

    def _worker_status(self, worker: str):
        try:
            return worker_base.WorkerServerStatus(name_resolve.get(
                names.worker_status(self.spec.experiment_name,
                                    self.spec.trial_name, worker)))
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return None

    def _maybe_reexpand(self):
        """Detect rejoined workers (relaunched incarnation: fresh
        heartbeat, RUNNING status, stale preempt notice cleared at its
        startup) and re-expand: release adopted replicas, restore the
        original routing, forgive exclusion history. The rejoined
        worker's replica self-heals to the current weights through the
        ordinary cross-group param-sync stream."""
        rejoined = []
        for w in sorted(self._retiring):
            if not self.watchdog.has_fresh_beat(w):
                continue
            if self.watchdog.preempt_notice(w) is not None:
                continue  # old incarnation still draining
            if self._worker_status(w) != \
                    worker_base.WorkerServerStatus.RUNNING:
                continue
            try:
                # the new incarnation's SUB socket must prove it
                # receives our PUB before any dispatch re-routes to it
                self.stream.wait_subscribers([w], timeout=5)
            except TimeoutError:
                continue  # retry on a later poll
            rejoined.append(w)
        if not rejoined:
            return
        for w in rejoined:
            self._retiring.discard(w)
            self._preempt_seen.discard(w)
            self._exclusions.forgive(w)
            metrics.inc("elastic_rejoin_total", worker=w)
            flight.record("elastic_rejoin", worker=w)
            logger.warning("Worker %s REJOINED; re-expanding.", w)
        if self.elastic is None:
            return
        available = {w for w in self.all_workers
                     if w not in self._retiring
                     and w not in self.watchdog.lost_workers()}
        for rec in self.elastic.restorable_nodes(available):
            rids = self.stream.request(
                rec.adopted_workers, "release_node",
                datas=[dict(node=rec.node)] * len(rec.adopted_workers))
            for w, r in zip(rec.adopted_workers, rids):
                self._inflight[r] = (None, None, w, "release")
            self.node_workers[rec.node] = list(rec.original_workers)
            self.node_worker[rec.node] = rec.original_workers[0]
            if rec.original_cross_group:
                self.cross_group_nodes.add(rec.node)
            else:
                self.cross_group_nodes.discard(rec.node)
            self.elastic.mark_restored(rec.node)
            logger.warning(
                "RE-EXPANDED %s: %s -> %s (degraded for %.1fs); "
                "param-sync refresh heals the rejoined replica "
                "forward.", rec.node, rec.adopted_workers,
                rec.original_workers, time.monotonic() - rec.since)

    def _dispatch_mfc(self, asm):
        mfc_name = asm.mfc
        node = self.dfg.find(mfc_name)
        workers = self.node_workers[mfc_name]
        leader = self.node_worker[mfc_name]
        # per-key/per-owner plan: samples of one assembly may span
        # dataset batches and (after an elastic reroute) be homed on
        # different workers
        fetch_plan = {k: {o: list(oids) for o, oids in owners.items()}
                      for k, owners
                      in self.buffer.assembly_plan(asm.aid).items()}
        payload = dict(node=mfc_name, ids=list(asm.sids),
                       fetch_plan=fetch_plan)
        if mfc_name in self.cross_group_nodes \
                and node.role in self._role_version:
            payload["param_sync"] = self._attach_param_sync(node)
        # the dispatch span parents to the step span of the assembly's
        # FIRST sample's batch; its context rides in the payloads so
        # worker-side MFC spans nest under it across the process
        # boundary
        step_span = self._step_spans.get(asm.primary_bid)
        with tracing.span(
                f"dispatch:{mfc_name}",
                parent=step_span.context if step_span else None,
                batch_id=asm.primary_bid, assembly=asm.aid,
                n_seqs=len(asm.sids), mfc=mfc_name, role=node.role,
                workers=",".join(workers)) as sp:
            rids = self.stream.request(
                workers, node.interface_type.value,
                datas=[payload] * len(workers),
                trace_ctx=sp.context.to_dict() if sp.context else None)
        for w, rid in zip(workers, rids):
            self._inflight[rid] = (asm.aid, mfc_name, w,
                                   "leader" if w == leader else "member")
        self._aid_bid[asm.aid] = asm.primary_bid
        self.buffer.mark_assembly_dispatched(asm.aid)
        logger.debug("Dispatched %s (assembly %d: %d seqs, batch %d) "
                     "to %s.", mfc_name, asm.aid, len(asm.sids),
                     asm.primary_bid, workers)

    def _attach_param_sync(self, node) -> Dict:
        """Cross-group weight flow (reference param_realloc hooks,
        _attach_payloads_with_hooks master_worker.py:296): when the
        role trained since the last sync to this node, dispatch a
        collective host-gather to the primary group; the exec group's
        request carries the expected version + where to fetch it."""
        from realhf_tpu.api.dfg import ParamReallocHook

        role = node.role
        version = self._role_version[role]
        eta = next((h.eta for h in node._pre_hooks
                    if isinstance(h, ParamReallocHook)
                    and h.eta is not None), 1.0)
        if version > self._last_synced.get(role, 0):
            senders = self.role_workers[role]
            rids = self.stream.request(
                senders, "param_sync_send",
                datas=[dict(role=role, version=version)] * len(senders))
            for w, r in zip(senders, rids):
                self._inflight[r] = (None, None, w, "sync")
            self._last_synced[role] = version
        # nonce: unique per dispatch -- the exec group's members agree
        # on ONE exact installed version under this key (a stale key
        # from an earlier dispatch must never leak into a later one).
        self._sync_nonce += 1
        return dict(role=role, version=version,
                    src=self.role_workers[role][0], eta=eta,
                    nonce=self._sync_nonce)

    def _dispatch_fetch(self):
        rid = self.stream.request(
            [self.data_owner], "fetch_data",
            datas=[dict(skip_ids=list(self._ids_to_skip))])[0]
        self._inflight[rid] = (None, None, self.data_owner, "fetch")
        self._fetch_inflight = True

    # ------------------------------------------------------------------
    def _on_fetch_reply(self, data: Dict):
        self._fetch_inflight = False
        # every reply -- empty included -- advanced the owner's loader
        self._fetches_done += 1
        epoch = self._start_epoch + data["epoch"]
        if data["is_epoch_last"]:
            self._epochs_fetched += 1
            # consumed-id skipping only applies to the resumed epoch
            self._ids_to_skip.clear()
            if self._start_epoch + self._epochs_fetched >= \
                    self.spec.total_train_epochs:
                self._done_fetching = True
        if data["empty"]:
            return
        bid = self.buffer.put_batch(data["meta"], self.data_owner, epoch,
                                    data["is_epoch_last"])
        self._step_spans[bid] = tracing.start_span(
            "step", batch_id=bid, epoch=epoch, worker=self.worker_name)

    def _on_mfc_reply(self, aid: int, mfc_name: str, data: Dict):
        node = self.dfg.find(mfc_name)
        worker = self.node_worker[mfc_name]
        self.buffer.complete_assembly(aid, data.get("meta"), worker)
        self._mfc_requeues.pop((aid, mfc_name), None)
        self._fetch_failed.pop((aid, mfc_name), None)
        stats = data.get("stats")
        if stats:
            self._step_stats.setdefault(mfc_name, {}).update(stats)
            if node.log_return_value:
                # structured JSONL through the metrics registry is the
                # record of record; the human-readable line drops to
                # DEBUG (docs/observability.md)
                metrics.event("mfc_stats", mfc=mfc_name, assembly=aid,
                              batch_id=self._aid_bid.get(aid),
                              role=node.role, stats=stats)
                logger.debug(
                    "MFC %s (assembly %d) stats: %s", mfc_name, aid,
                    {k: round(v, 4) if isinstance(v, float) else v
                     for k, v in stats.items()})
        if node.interface_type == ModelInterfaceType.TRAIN_STEP:
            self._role_version[node.role] += 1

    def _finish_batches(self):
        for e in self.buffer.pop_finished():
            self._min_live_bid = max(self._min_live_bid, e.batch_id + 1)
            # requeue/fetch-cooldown records are pruned per assembly on
            # completion; the aid->bid anchor map is swept by size (a
            # member reply can trail its assembly arbitrarily)
            if len(self._aid_bid) > 4096:
                for aid in sorted(self._aid_bid)[:-2048]:
                    del self._aid_bid[aid]
            self.global_step += 1
            self._cur_epoch = e.epoch
            self._consumed_ids.extend(e.ids)
            dt = (time.monotonic() - self._step_t0
                  if self._step_t0 else 0.0)
            self._step_t0 = time.monotonic()
            step_span = self._step_spans.pop(e.batch_id, None)
            if step_span is not None:
                step_span.set_attribute("global_step", self.global_step)
                step_span.finish()
            metrics.inc("master_steps_total")
            metrics.observe("master_step_secs", dt)
            # progress beacon: pod controllers / chaos harnesses watch
            # trial progress without a control-panel socket
            try:
                name_resolve.add(
                    names.train_progress(self.spec.experiment_name,
                                         self.spec.trial_name),
                    str(self.global_step), replace=True,
                    delete_on_exit=False)
            except Exception:  # noqa: BLE001 - advisory only
                pass
            logger.info(
                "Master: batch %d done (global step %d, epoch %d) "
                "%.2fs since last; stats keys: %s", e.batch_id,
                self.global_step, e.epoch, dt,
                sorted(self._step_stats))
            # free worker-side storage for this batch (active workers
            # only: a retiring worker's store dies with it, and its
            # unanswered clears would pile up in _inflight forever)
            targets = self._active_workers()
            rids = self.stream.request(
                targets, "clear_data_cache",
                datas=[dict(ids=list(e.ids))] * len(targets))
            for w, r in zip(targets, rids):
                self._inflight[r] = (None, None, w, "clear")
            self._log_device_stats(e.batch_id)
            self._maybe_save_eval(e)
            if e.is_epoch_last:
                self._consumed_ids = []
            if (self.spec.ctl.benchmark_steps is not None
                    and self.global_step >= self.spec.ctl.benchmark_steps):
                self._complete = True

    def _log_device_stats(self, bid: int):
        """Per-MFC device stats for a finished batch (reference
        __log_gpu_stats all-gathered table, model_worker.py:999-1094).
        Structured JSONL through the metrics registry is the primary
        emission (machine-diffable across runs); the human-readable
        table is kept at DEBUG."""
        rows = [r for r in self._exec_log if r.get("bid") == bid]
        if not rows:
            return
        t0 = min(r["start"] for r in rows)
        for r in sorted(rows, key=lambda r: r["start"]):
            metrics.event(
                "mfc_device_stats", batch_id=bid, mfc=r["mfc"],
                worker=r["worker"], secs=r["secs"],
                hbm_bytes_in_use=r["hbm_bytes_in_use"],
                proc_peak_hbm_bytes=r["proc_peak_hbm_bytes"],
                rel_start=round(r["start"] - t0, 4),
                rel_end=round(r["end"] - t0, 4))
            metrics.observe("mfc_exec_secs", r["secs"], mfc=r["mfc"],
                            worker=r["worker"])
        if logger.isEnabledFor(10):  # DEBUG
            lines = ["MFC device stats (batch %d):" % bid,
                     f"  {'mfc':<16} {'worker':<18} {'secs':>8} "
                     f"{'hbm_now':>10} {'proc_peak':>10}"]
            for r in sorted(rows, key=lambda r: r["start"]):
                lines.append(
                    f"  {r['mfc']:<16} {r['worker']:<18} "
                    f"{r['secs']:>8.3f} "
                    f"{r['hbm_bytes_in_use'] / 2 ** 30:>9.2f}G "
                    f"{r['proc_peak_hbm_bytes'] / 2 ** 30:>9.2f}G "
                    f"[{r['start'] - t0:+.3f}s..{r['end'] - t0:+.3f}s]")
            logger.debug("\n".join(lines))
        # Prune every ALREADY-LOGGED batch's rows (not `> bid`: with
        # off-policy overlap an EARLIER batch can still be live when a
        # later one finishes, advisor r3; not `!= bid` alone either:
        # member rows arriving after their batch was logged would then
        # never be swept and the log would grow unboundedly).
        self._logged_bids.add(bid)
        # Sweep rows of logged batches AND any stragglers of batches
        # that already left the live window (a late member row whose
        # bid dropped out of the set below would otherwise stick
        # forever), THEN bound the set by the live window -- membership
        # only matters while a batch can still emit late rows. Order
        # matters: pruning the set first would empty it (the just-
        # logged bid is below the already-advanced _min_live_bid) and
        # make the row sweep a no-op, growing _exec_log unboundedly.
        min_live = self._min_live_bid
        self._exec_log = [r for r in self._exec_log
                          if r.get("bid") is not None
                          and r["bid"] not in self._logged_bids
                          and r["bid"] >= min_live]
        self._logged_bids = {b for b in self._logged_bids
                             if b >= min_live}

    def _maybe_save_eval(self, entry, force=False):
        train_nodes = [m for ms in self.train_nodes_of_role.values()
                       for m in ms]
        if not train_nodes:
            return
        epochs = 1 if entry is not None and entry.is_epoch_last else 0
        if force or self.save_ctl.check(epochs=epochs, steps=1):
            by_worker: Dict[str, list] = {}
            for m in train_nodes:
                for w in self.node_workers[m]:
                    by_worker.setdefault(w, []).append(m)
            # post ALL save requests first, then gather: workers
            # checkpoint concurrently instead of one at a time.
            # Retried with backoff (save is idempotent); each attempt
            # is liveness-checked so a dead worker aborts it within
            # the heartbeat timeout, not after gather_timeout_secs.
            replies = self._request_gather_with_retry("save", by_worker)
            # durable-checkpoint manifests (system/ckpt_manager.py):
            # workers reply {role: {path, manifest, step}} after the
            # atomic commit; the newest manifest per role rides in
            # RecoverInfo v3 so a resumed trial restores the exact
            # weights these counters describe.
            for p in replies:
                if not isinstance(p.data, dict):
                    continue
                for role, v in p.data.items():
                    if isinstance(v, dict) and v.get("manifest"):
                        self._ckpt_manifests[role] = v["manifest"]
            if self.recover_mode != "disabled":
                recover.dump(recover.RecoverInfo(
                    recover_start=recover.StepInfo(
                        epoch=self._cur_epoch, epoch_step=0,
                        global_step=self.global_step),
                    last_step_info=recover.StepInfo(
                        epoch=self._cur_epoch, epoch_step=0,
                        global_step=self.global_step),
                    hash_vals_to_ignore=list(self._consumed_ids),
                    buffer_state=self.buffer.state_dict(),
                    dataloader_state=dict(
                        epoch=self._cur_epoch,
                        epochs_fetched=self._epochs_fetched),
                    ckpt_manifests=dict(self._ckpt_manifests) or None))
        if self.spec.eval_dataset is not None and not force and \
                self.eval_ctl.check(epochs=epochs, steps=1):
            by_worker = {}
            for m in train_nodes:
                for w in self.node_workers[m]:
                    by_worker.setdefault(w, []).append(m)
            for p in self._request_gather_with_retry("evaluate",
                                                     by_worker):
                if p.data:
                    logger.info("Eval results: %s", p.data)

    def _request_gather_with_retry(self, handle: str,
                                   by_worker: Dict[str, list]):
        """Dispatch ``handle`` to each worker and gather, retrying
        the whole round with exponential backoff + jitter on reply
        timeout (control-plane retry policy; WorkerLostError is never
        retried -- a dead worker needs relaunch-level recovery)."""

        def attempt():
            rids = [self.stream.request(
                [w], handle,
                datas=[dict(nodes=nodes,
                            global_step=self.global_step)])[0]
                for w, nodes in by_worker.items()]
            try:
                return self.stream.gather_replies(
                    rids, timeout=self.ft.gather_timeout_secs,
                    check_liveness=lambda: self.watchdog.raise_if_lost(
                        by_worker,
                        inflight=[f"{handle}:{sorted(ns)}"
                                  for ns in by_worker.values()]))
            finally:
                self.stream.discard(rids)

        return retry_call(
            attempt,
            RetryPolicy(max_attempts=max(1, self.ft.gather_retries),
                        base_delay=1.0,
                        max_elapsed=getattr(
                            self.ft, "gather_max_elapsed_secs", None)),
            retry_on=(TimeoutError,), what=f"{handle} gather")

    # ------------------------------------------------------------------
    def _poll(self) -> worker_base.PollResult:
        if self._complete:
            time.sleep(0.05)
            return worker_base.PollResult(0, 0)
        if not self._subscribed:
            # liveness-checked: a worker that died during configure
            # aborts the wait promptly with attribution instead of
            # after the full 300 s
            self.stream.wait_subscribers(
                self.all_workers, timeout=300,
                check_liveness=self.watchdog.raise_if_lost)
            self._subscribed = True
            self._publish_status("running")
            self._step_t0 = time.monotonic()

        # 0. watchdog: requeue/fail work on lost workers (rate-limited
        # internally, so this is cheap every iteration)
        self._check_liveness()

        n = 0
        # 1. keep the buffer fed
        if (self.buffer.has_space and not self._fetch_inflight
                and not self._done_fetching
                and self._workers_eligible([self.data_owner])):
            self._dispatch_fetch()
            n += 1

        # 2. assemble + dispatch every input-ready MFC batch from the
        # per-sample pool (subject to the off-policyness guard). Once
        # fetching is done and upstream MFCs drain, partial tail
        # assemblies flush so per-MFC n_seqs need not divide the data.
        flush = ([n_.name for n_ in self.dfg.nodes]
                 if self._done_fetching and not self._fetch_inflight
                 else ())
        for asm in self.buffer.ready_assemblies(flush=flush):
            if self._dispatchable(asm):
                self._dispatch_mfc(asm)
                n += 1
        # overlap observability: how many samples sit ready per MFC
        # (docs/observability.md; the Perfetto timeline pairs this
        # with the dispatch/step spans)
        for m in self.n_seqs_of:
            metrics.set_gauge("buffer_ready_samples",
                              self.buffer.ready_count(m), mfc=m)

        # 3. collect replies
        for p in self.stream.poll_batch(timeout=0.05):
            if p.handle_name == "error":
                raise RuntimeError(
                    f"Model worker reported error: {p.data}")
            ref = self._inflight.pop(p.request_id, None)
            if ref is None:
                continue
            aid, mfc_name, worker, kind = ref
            if kind in ("leader", "member") \
                    and isinstance(p.data, dict) \
                    and p.data.get("fetch_failed"):
                self._on_mfc_fetch_failed(aid, mfc_name, worker,
                                          p.data["fetch_failed"])
                n += 1
                continue
            if kind == "fetch":
                self._on_fetch_reply(p.data)
            elif kind in ("leader", "member"):
                info = (p.data.get("exec_info")
                        if isinstance(p.data, dict) else None)
                if info:
                    row = dict(info, mfc=mfc_name, worker=worker,
                               bid=self._aid_bid.get(aid))
                    self._exec_log.append(row)
                    # history is appended ON ARRIVAL (bounded): a
                    # member row landing after its batch was logged
                    # must still reach the stats command
                    self._exec_history.append(row)
                    del self._exec_history[:-512]
                if kind == "leader":
                    self._on_mfc_reply(aid, mfc_name, p.data)
            n += 1

        # 4. batch completion accounting
        self._finish_batches()
        # Checked OUTSIDE the pop loop: when every remaining fetch
        # returns empty (e.g. resume where the final epoch was fully
        # consumed) no batch ever finishes, yet the trial is done.
        if (not self._complete and self._done_fetching
                and len(self.buffer) == 0 and not self._fetch_inflight):
            self._complete = True
        if self._complete:
            self._maybe_save_eval(None, force=True)
            self._publish_status("done")
            logger.info("Master: experiment complete at global step %d.",
                        self.global_step)
        return worker_base.PollResult(n, n)

    def _handle_command(self, cmd, kwargs):
        if cmd == "stats":
            # history receives every row on arrival, so it alone is
            # the complete record (the working log would duplicate it)
            return dict(stats=self._step_stats,
                        global_step=self.global_step,
                        complete=self._complete,
                        exec_log=list(self._exec_history),
                        # host failure domains: the HOST_LOST
                        # attribution history ({host, workers, ts}) --
                        # the pod e2e's acceptance surface
                        host_lost=self.watchdog.host_lost_events())
        if cmd == "profiler":
            # master control surface for jax.profiler: broadcast the
            # start/stop to every active model worker (the master
            # itself runs no device code worth profiling); replies
            # drain through the ordinary poll loop
            action = (kwargs or {}).get("action", "start")
            targets = self._active_workers()
            rids = self.stream.request(
                targets, "profiler",
                datas=[dict(kwargs or {}, action=action)] * len(targets))
            for w, r in zip(targets, rids):
                self._inflight[r] = (None, None, w, "profiler")
            flight.record("profiler_broadcast", action=action,
                          n_workers=len(targets))
            return dict(action=action, requested=targets)
        return super()._handle_command(cmd, kwargs)

    def _exit_hook(self):
        # a trial that survived host losses leaves its postmortem on
        # disk even on a CLEAN exit: the launcher's teardown merges
        # per-host dumps into one incident timeline (obs/flight.py)
        if getattr(self, "watchdog", None) is not None \
                and self.watchdog.host_lost_events():
            flight.dump(reason="host loss survived (postrun record)")
        # close out still-open step spans so the merged trace shows
        # the in-flight batches of an interrupted trial too
        for sp in getattr(self, "_step_spans", {}).values():
            sp.set_attribute("unfinished", True)
            sp.finish()
        if getattr(self, "_step_spans", None):
            self._step_spans.clear()
        tracing.flush()
        if getattr(self, "stream", None) is not None:
            self.stream.close()
