"""Host-level data plane between model workers.

TPU-native counterpart of reference ``impl/model/comm/
data_transfer.py``: there, MFC outputs move producer->consumer over
NCCL broadcast groups. Here every worker runs a small threaded data
server; a consumer worker fetches the per-sequence pieces it needs by
(ids, keys) over ZMQ (the host/DCN relay of SURVEY §5.8 -- device
tensors were already pulled to host as numpy when the producing MFC
stored its output). Device-to-device transfer inside one worker's mesh
never touches this path; cross-host device meshes use
``jax.distributed`` (``parallel/multihost.py``).

The server thread only ever reads the store; writes happen in the
worker's poll thread. A lock guards the dict itself (values are
immutable once inserted).
"""

import pickle
import threading
from typing import Dict, Hashable, List, Tuple

import zmq

from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import logging, name_resolve, names, network

logger = logging.getLogger("data_plane")


def _pickle_frames(obj) -> list:
    """Serialize a reply as [pickle5-header, buffer frames...]: numpy
    payloads serialize out-of-band (no pickle copy of the array
    bytes), which is the difference between ~0.3 and multiple GB/s on
    parameter-sync blobs. The paired receiver is _recv_zero_copy.
    Split from the send so a serialization failure (e.g. a
    non-contiguous PickleBuffer) never leaves a REP socket mid-send."""
    bufs = []
    head = pickle.dumps(obj, protocol=5, buffer_callback=bufs.append)
    return [head] + [b.raw() for b in bufs]


def _recv_zero_copy(sock):
    frames = sock.recv_multipart(copy=False)
    return pickle.loads(frames[0].buffer,
                        buffers=[f.buffer for f in frames[1:]])


def data_server_key(experiment_name: str, trial_name: str,
                    worker_name: str) -> str:
    return (names.trial_root(experiment_name, trial_name)
            + f"/data_server/{worker_name}")


class DataStore:
    """id -> single-sequence SequenceSample (all keys merged in).

    The worker's storage of MFC inputs/outputs (reference
    ``model_worker.__data_storage``, model_worker.py:368-399).
    """

    def __init__(self):
        self._store: Dict[Hashable, SequenceSample] = {}
        # name -> (version, payload): versioned blobs for cross-group
        # parameter sync (only the latest version is kept; receivers
        # accept any version >= the one they were told to expect)
        self._blobs: Dict[str, Tuple[int, object]] = {}
        self._lock = threading.Lock()

    def put_blob(self, name: str, version: int, payload):
        with self._lock:
            cur = self._blobs.get(name)
            if cur is None or cur[0] <= version:
                self._blobs[name] = (version, payload)

    def get_blob(self, name: str, min_version: int):
        """(version, payload) if a blob with version >= min_version is
        stored, else (latest stored version or -1, None)."""
        with self._lock:
            cur = self._blobs.get(name)
            if cur is not None and cur[0] >= min_version:
                return cur
            return (cur[0] if cur is not None else -1, None)

    def gc_blobs(self, prefix: str, keep_versions):
        """Drop blobs under ``prefix`` whose version is not in
        ``keep_versions`` (bounds sender memory to the retained
        chunk-set generations)."""
        keep = set(keep_versions)
        with self._lock:
            for name in [n for n in self._blobs if n.startswith(prefix)]:
                if self._blobs[name][0] not in keep:
                    del self._blobs[name]

    def put(self, sample: SequenceSample):
        """Merge a (possibly multi-sequence) sample into the store.

        Copy-on-write: the merge happens on a CLONE outside the lock
        and the finished value is swapped in -- stored values really
        are immutable once inserted, so readers (``get``) may run
        ``select``/``gather`` on their references without holding the
        lock. Single writer (the worker's poll thread); the lock only
        orders the dict accesses against readers."""
        for piece in sample.unpack():
            sid = piece.ids[0]
            with self._lock:
                cur = self._store.get(sid)
            if cur is not None:
                merged = SequenceSample(
                    keys=cur.keys, trailing_shapes=cur.trailing_shapes,
                    dtypes=cur.dtypes, ids=cur.ids,
                    seqlens=cur.seqlens,
                    data=None if cur.data is None else dict(cur.data),
                    metadata=cur.metadata)
                merged.update_(piece)
                piece = merged
            with self._lock:
                self._store[sid] = piece

    def get(self, ids: List[Hashable], keys: List[str]
            ) -> SequenceSample:
        # hold the lock only for the dict reads; the per-sequence
        # select and the gather concatenation (the expensive, numpy-
        # copying part) run on immutable snapshots outside it
        with self._lock:
            pieces = [self._store[i] for i in ids]
        return SequenceSample.gather(
            [p.select(list(keys)) for p in pieces])

    def has(self, sid: Hashable, keys: List[str]) -> bool:
        with self._lock:
            s = self._store.get(sid)
            return s is not None and all(k in s.keys for k in keys)

    def clear(self, ids: List[Hashable]):
        with self._lock:
            for i in ids:
                self._store.pop(i, None)

    def __len__(self):
        with self._lock:
            return len(self._store)


class DataServer(threading.Thread):
    """Replies to (ids, keys) fetches from the worker's DataStore."""

    def __init__(self, experiment_name: str, trial_name: str,
                 worker_name: str, store: DataStore):
        super().__init__(daemon=True, name=f"data-server-{worker_name}")
        self.store = store
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.REP)
        port = self._sock.bind_to_random_port("tcp://*")
        self.address = f"tcp://{network.gethostip()}:{port}"
        name_resolve.add(
            data_server_key(experiment_name, trial_name, worker_name),
            self.address, replace=True)
        self._stop_evt = threading.Event()

    def run(self):
        while not self._stop_evt.is_set():
            if not self._sock.poll(100):
                continue
            raw = self._sock.recv()
            # once recv'd, the REP socket MUST send before the next
            # recv -- reply with an error rather than dying silently
            # (a dead server turns every peer fetch into a timeout)
            try:
                msg = pickle.loads(raw)
                if isinstance(msg, tuple) and msg and msg[0] == "blob":
                    _, name, min_version = msg
                    version, payload = self.store.get_blob(name,
                                                           min_version)
                    if payload is None:
                        reply = ("pending", version)
                    else:
                        reply = ("ok", (version, payload))
                else:
                    ids, keys = msg
                    payload = self.store.get(ids, keys)
                    reply = ("ok", payload)
            except Exception as e:  # noqa: BLE001 - reply, don't die
                logger.error("Data server request failed: %r", e)
                reply = ("error", repr(e))
            # A REP socket must send EXACTLY once per recv. Pickling
            # is split from sending so a serialization failure (e.g. a
            # non-contiguous PickleBuffer in .raw()) can still become
            # an error reply; but once any frame may have hit the wire
            # a second send would be EFSM and kill this thread, so the
            # fallback only fires when nothing was sent. The error
            # path uses copy=True: no zero-copy machinery to fail.
            try:
                frames = _pickle_frames(reply)
            except Exception as e:  # noqa: BLE001 - serialize error
                logger.error("Data server reply pickling failed: %r", e)
                frames = [pickle.dumps(("error", repr(e)))]
            maybe_sent = False
            try:
                if len(frames) == 1:
                    self._sock.send(frames[0], copy=True)
                else:
                    maybe_sent = True  # multipart may partially send
                    self._sock.send_multipart(frames, copy=False)
            except Exception as e:  # noqa: BLE001 - reply, don't die
                logger.error("Data server reply send failed: %r", e)
                if not maybe_sent:
                    try:
                        self._sock.send(
                            pickle.dumps(("error", repr(e))), copy=True)
                    except Exception:  # noqa: BLE001 - peer times out
                        logger.error(
                            "Data server error-reply send failed too; "
                            "peer fetch will time out.")

    def stop(self):
        self._stop_evt.set()
        self.join(timeout=2)
        self._sock.close(0)


class DataClient:
    """Fetch-side cache of connections to peer data servers."""

    def __init__(self, experiment_name: str, trial_name: str):
        self._exp, self._trial = experiment_name, trial_name
        self._ctx = zmq.Context.instance()
        # worker name -> (registered address, REQ socket)
        self._socks: Dict[str, tuple] = {}

    def _sock_for(self, worker_name: str) -> zmq.Socket:
        # revalidate against the peer's CURRENT registration: a
        # relaunched worker (elastic rejoin, pod host back from
        # preemption) re-registers its data server at a new address,
        # and a REQ cached against the dead incarnation would block
        # the full fetch timeout before healing
        addr = name_resolve.wait(
            data_server_key(self._exp, self._trial, worker_name),
            timeout=60)
        cached = self._socks.get(worker_name)
        if cached is not None:
            if cached[0] == addr:
                return cached[1]
            logger.info("Data server %s re-registered (%s -> %s); "
                        "reconnecting.", worker_name, cached[0], addr)
            cached[1].close(0)
            del self._socks[worker_name]
        s = self._ctx.socket(zmq.REQ)
        try:
            s.connect(addr)
        except BaseException:
            # a bad registered address must not leak the socket
            # (graft-lint lifecycle-leak-on-raise)
            s.close(0)
            raise
        self._socks[worker_name] = (addr, s)
        return s

    def fetch(self, worker_name: str, ids: List[Hashable],
              keys: List[str], timeout: float = 300.0) -> SequenceSample:
        s = self._sock_for(worker_name)
        s.send(pickle.dumps((list(ids), list(keys))))
        if not s.poll(timeout * 1000):
            s.close(0)  # REQ stuck between send and recv
            self._socks.pop(worker_name, None)
            raise TimeoutError(
                f"Data fetch from {worker_name} timed out "
                f"({len(ids)} ids, keys={keys}).")
        status, payload = _recv_zero_copy(s)
        if status != "ok":
            raise RuntimeError(
                f"Data fetch from {worker_name} failed: {payload}")
        return payload

    def fetch_blob(self, worker_name: str, name: str, min_version: int,
                   timeout: float = 300.0):
        """Fetch a versioned blob, POLLING until the owner has
        published version >= min_version (the sender may still be
        gathering when the receiver asks -- both sides were dispatched
        together by the master)."""
        import time as _time

        s = self._sock_for(worker_name)
        deadline = _time.monotonic() + timeout
        while True:
            s.send(pickle.dumps(("blob", name, min_version)))
            remaining = deadline - _time.monotonic()
            if remaining <= 0 or not s.poll(remaining * 1000):
                # a REQ socket abandoned between send and recv is
                # stuck in the receive state -- drop it so the next
                # fetch through _sock_for starts clean
                s.close(0)
                self._socks.pop(worker_name, None)
                raise TimeoutError(
                    f"Blob fetch {name} v>={min_version} from "
                    f"{worker_name} timed out.")
            status, payload = _recv_zero_copy(s)
            if status == "ok":
                return payload  # (version, value)
            if status == "error":
                raise RuntimeError(
                    f"Blob fetch {name} from {worker_name} failed: "
                    f"{payload}")
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"Blob {name} v>={min_version} not published by "
                    f"{worker_name} within {timeout}s (have "
                    f"v{payload}).")
            _time.sleep(0.05)

    def close(self):
        for _addr, s in self._socks.values():
            s.close(0)
