"""Flight recorder: a bounded ring of recent events per worker.

The postmortem half of the observability layer
(docs/observability.md): every worker keeps the last N runtime events
(requests handled, replies sent, faults fired, preemption notices,
weight swaps, ...) in a fixed-size in-memory ring. Recording is a
deque append under a lock -- cheap enough for hot paths -- and
nothing touches disk until something goes wrong: injected
``fault_injection`` crashes, preemption hooks, and
``WorkerLostError``/ERROR exit paths call :func:`dump`, which writes
the ring as one JSON file under ``{run_log_path}/obs/flight/`` so the
operator sees exactly what the process did right before it died.

Dump format (``docs/observability.md`` has the catalog)::

    {"worker": ..., "reason": ..., "dumped_at": <wall ts>,
     "n_events": N, "events": [{"ts": ..., "kind": ..., ...}, ...]}
"""

import collections
import json
import os
import threading
import time
from typing import Deque, Dict, List, Optional

from realhf_tpu.base import logging

logger = logging.getLogger("obs.flight")

DEFAULT_CAPACITY = 512


class FlightRecorder:
    """Bounded event ring + crash-time dump for one process."""

    def __init__(self, name: str = "proc",
                 capacity: int = DEFAULT_CAPACITY):
        self.name = name
        self.capacity = capacity
        self._events: Deque[Dict] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def configure(self, name: str):
        self.name = name

    def record(self, event_kind: Optional[str] = None, **detail):
        """Append one event. The positional is the EVENT kind (stored
        under ``"kind"`` in the dump); it used to be named ``kind``,
        which made any ``kind=`` detail kwarg a TypeError at the call
        site. Now a ``kind=`` detail is legal: with a positional event
        kind present it lands in the event as ``kind_detail`` (the
        event kind owns the ``"kind"`` slot); without one it is taken
        as the event kind itself (deprecated keyword spelling)."""
        if event_kind is None:
            if "kind" not in detail:
                raise TypeError("record() needs an event kind "
                                "(positional event_kind)")
            event_kind = detail.pop("kind")
            _warn_kind_kwarg_once()
        elif "kind" in detail:
            detail["kind_detail"] = detail.pop("kind")
        ev = dict(ts=time.time(), kind=event_kind, **detail)
        with self._lock:
            self._events.append(ev)

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def dump(self, reason: str, path: Optional[str] = None
             ) -> Optional[str]:
        """Write the ring to ``path`` (default: this run's flight
        dir). Returns the written path, or None when writing failed --
        a postmortem must never mask the original failure."""
        events = self.events()
        record = dict(worker=self.name, reason=reason,
                      dumped_at=time.time(), n_events=len(events),
                      events=events)
        if path is None:
            try:
                path = dump_path(self.name)
            except Exception as e:  # noqa: BLE001 - run constants may
                # be unset in unit-test contexts; fall back loudly
                logger.warning("Flight dump path unavailable (%s); "
                               "dropping dump for %s.", e, self.name)
                return None
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(record, f, indent=1, default=str)
            os.replace(tmp, path)
        except OSError as e:
            logger.warning("Flight dump to %s failed: %s", path, e)
            return None
        logger.warning("Flight recorder dumped %d events to %s "
                       "(reason: %s).", len(events), path, reason)
        return path


_warned_kind_kwarg = False


def _warn_kind_kwarg_once():
    global _warned_kind_kwarg
    if not _warned_kind_kwarg:
        _warned_kind_kwarg = True
        logger.warning(
            "flight.record(kind=...) as the event kind is deprecated; "
            "pass it positionally (record(event_kind, **detail)).")


def flight_dir(experiment: Optional[str] = None,
               trial: Optional[str] = None) -> str:
    from realhf_tpu.base import constants
    return os.path.join(constants.run_log_path(experiment, trial),
                        "obs", "flight")


def dump_path(process_name: str,
              experiment: Optional[str] = None,
              trial: Optional[str] = None) -> str:
    safe = process_name.replace("/", "-").replace(" ", "_")
    return os.path.join(flight_dir(experiment, trial),
                        f"{safe}.flight.json")


# ----------------------------------------------------------------------
_default = FlightRecorder()


def default_recorder() -> FlightRecorder:
    return _default


def reset_default():
    """Fresh default recorder (test isolation)."""
    global _default
    _default = FlightRecorder()


def configure(name: str):
    _default.configure(name)


def record(event_kind: Optional[str] = None, **detail):
    _default.record(event_kind, **detail)


def dump(reason: str, path: Optional[str] = None) -> Optional[str]:
    return _default.dump(reason, path=path)


MERGED_DUMP_NAME = "merged_flight.json"


def merge_dumps(directory: Optional[str] = None,
                out_path: Optional[str] = None,
                experiment: Optional[str] = None,
                trial: Optional[str] = None) -> Optional[str]:
    """Fold every per-worker ``*.flight.json`` under ``directory``
    (default: this run's flight dir) into one time-sorted postmortem
    (``merged_flight.json``): each event gains its worker (and, when
    the dump recorded one, host) label so a pod-wide incident reads as
    a single interleaved story. Returns the merged path, or None when
    there was nothing to merge; unreadable dumps are skipped -- a
    worker killed mid-dump must not void everyone else's ring."""
    directory = directory or flight_dir(experiment, trial)
    if not os.path.isdir(directory):
        return None
    merged_events: List[Dict] = []
    workers: List[str] = []
    reasons: Dict[str, str] = {}
    for fn in sorted(os.listdir(directory)):
        if not fn.endswith(".flight.json"):
            continue
        try:
            with open(os.path.join(directory, fn)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        worker = rec.get("worker", fn[:-len(".flight.json")])
        workers.append(worker)
        reasons[worker] = rec.get("reason", "")
        for ev in rec.get("events", ()):
            if isinstance(ev, dict):
                merged_events.append(dict(ev, worker=worker))
    if not workers:
        return None
    merged_events.sort(key=lambda e: (e.get("ts") or 0.0))
    out_path = out_path or os.path.join(directory, MERGED_DUMP_NAME)
    record = dict(n_dumps=len(workers), workers=sorted(workers),
                  reasons=reasons, n_events=len(merged_events),
                  events=merged_events)
    tmp = f"{out_path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, default=str)
        os.replace(tmp, out_path)
    except OSError as e:
        logger.warning("Flight merge to %s failed: %s", out_path, e)
        return None
    logger.info("Merged %d flight events from %d dumps into %s.",
                len(merged_events), len(workers), out_path)
    return out_path
