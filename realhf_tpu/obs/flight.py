"""Flight recorder: a bounded ring of recent events per worker.

The postmortem half of the observability layer
(docs/observability.md): every worker keeps the last N runtime events
(requests handled, replies sent, faults fired, preemption notices,
weight swaps, ...) in a fixed-size in-memory ring. Recording is a
deque append under a lock -- cheap enough for hot paths -- and
nothing touches disk until something goes wrong: injected
``fault_injection`` crashes, preemption hooks, and
``WorkerLostError``/ERROR exit paths call :func:`dump`, which writes
the ring as one JSON file under ``{run_log_path}/obs/flight/`` so the
operator sees exactly what the process did right before it died.

Dump format (``docs/observability.md`` has the catalog)::

    {"worker": ..., "reason": ..., "dumped_at": <wall ts>,
     "n_events": N, "events": [{"ts": ..., "kind": ..., ...}, ...]}
"""

import collections
import json
import os
import threading
import time
from typing import Deque, Dict, List, Optional

from realhf_tpu.base import logging

logger = logging.getLogger("obs.flight")

DEFAULT_CAPACITY = 512


class FlightRecorder:
    """Bounded event ring + crash-time dump for one process."""

    def __init__(self, name: str = "proc",
                 capacity: int = DEFAULT_CAPACITY):
        self.name = name
        self.capacity = capacity
        self._events: Deque[Dict] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def configure(self, name: str):
        self.name = name

    def record(self, kind: str, **detail):
        # detail keys must not collide with the positional event kind
        # ("kind" in detail would TypeError at the call site -- use a
        # qualified key like fault_kind instead)
        ev = dict(ts=time.time(), kind=kind, **detail)
        with self._lock:
            self._events.append(ev)

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def dump(self, reason: str, path: Optional[str] = None
             ) -> Optional[str]:
        """Write the ring to ``path`` (default: this run's flight
        dir). Returns the written path, or None when writing failed --
        a postmortem must never mask the original failure."""
        events = self.events()
        record = dict(worker=self.name, reason=reason,
                      dumped_at=time.time(), n_events=len(events),
                      events=events)
        if path is None:
            try:
                path = dump_path(self.name)
            except Exception as e:  # noqa: BLE001 - run constants may
                # be unset in unit-test contexts; fall back loudly
                logger.warning("Flight dump path unavailable (%s); "
                               "dropping dump for %s.", e, self.name)
                return None
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(record, f, indent=1, default=str)
            os.replace(tmp, path)
        except OSError as e:
            logger.warning("Flight dump to %s failed: %s", path, e)
            return None
        logger.warning("Flight recorder dumped %d events to %s "
                       "(reason: %s).", len(events), path, reason)
        return path


def flight_dir(experiment: Optional[str] = None,
               trial: Optional[str] = None) -> str:
    from realhf_tpu.base import constants
    return os.path.join(constants.run_log_path(experiment, trial),
                        "obs", "flight")


def dump_path(process_name: str,
              experiment: Optional[str] = None,
              trial: Optional[str] = None) -> str:
    safe = process_name.replace("/", "-").replace(" ", "_")
    return os.path.join(flight_dir(experiment, trial),
                        f"{safe}.flight.json")


# ----------------------------------------------------------------------
_default = FlightRecorder()


def default_recorder() -> FlightRecorder:
    return _default


def reset_default():
    """Fresh default recorder (test isolation)."""
    global _default
    _default = FlightRecorder()


def configure(name: str):
    _default.configure(name)


def record(kind: str, **detail):
    _default.record(kind, **detail)


def dump(reason: str, path: Optional[str] = None) -> Optional[str]:
    return _default.dump(reason, path=path)
