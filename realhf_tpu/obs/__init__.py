"""Unified observability layer: tracing, metrics, flight recorder.

Three cooperating pieces (docs/observability.md):

- :mod:`realhf_tpu.obs.tracing` -- structured spans with trace/span
  ids, propagated across processes through ``request_reply_stream``
  payloads and the serving ZMQ envelope, exported as Chrome
  trace-event JSON (Perfetto-loadable).
- :mod:`realhf_tpu.obs.metrics` -- a counter/gauge/summary/histogram
  registry snapshotted periodically to JSONL and served as Prometheus
  text from the worker health surface (the ``metrics`` worker
  command).
- :mod:`realhf_tpu.obs.flight` -- a bounded ring of recent events per
  worker, dumped to disk on crashes, preemptions, and worker-lost
  paths for postmortems.
- :mod:`realhf_tpu.obs.http` -- live HTTP telemetry endpoints
  (/metrics, /healthz, /flight, /statusz) every worker and the inline
  runner serve on an ephemeral port published under
  ``names.telemetry`` (the Prometheus scrape surface).
- :mod:`realhf_tpu.obs.analyze` -- trace analytics: per-step
  wall-time attribution, critical-path/bottleneck-MFC, straggler
  skew, and goodput computed from the merged Chrome trace
  (``scripts/analyze_trace.py`` is the CLI).

:func:`configure_from_env` is the one call every process entry point
makes (``worker_base.Worker``, the inline runner, quickstart): it
labels the default tracer/registry/recorder with the process name and
turns file export on when ``REALHF_TPU_TRACE=1``.
"""

from typing import Optional

from realhf_tpu.obs import flight, metrics, tracing  # noqa: F401


def configure_from_env(process_name: str,
                       experiment: Optional[str] = None,
                       trial: Optional[str] = None):
    """Label the process-default tracer, metrics registry, and flight
    recorder, and enable trace/metrics file export per the env:

    - ``REALHF_TPU_TRACE=1``: span tracing ON, streamed to
      ``{run_log_path}/obs/trace/{process}.trace.jsonl`` (merged into
      one Chrome trace at trial teardown) and metrics snapshots to
      ``{run_log_path}/obs/metrics/{process}.metrics.jsonl``.
    - ``REALHF_TPU_METRICS_JSONL=<path-or-1>``: metrics JSONL sink
      alone (``1`` uses the default per-run path).

    Needs ``experiment``/``trial`` (or previously set run constants)
    to resolve file paths; with neither, export is skipped and only
    the labels apply. Never raises: observability setup must not take
    a worker down."""
    tracing.configure(process_name=process_name)
    metrics.default_registry().process_name = process_name
    flight.configure(process_name)
    import os

    trace_on = tracing.trace_env_enabled()
    metrics_env = os.environ.get(metrics.METRICS_JSONL_ENV, "")
    if not trace_on and not metrics_env:
        return
    try:
        if trace_on:
            tracing.configure(
                enabled=True,
                path=tracing.trace_file_path(process_name, experiment,
                                             trial))
        if metrics_env not in ("", "0") and metrics_env != "1":
            metrics.default_registry().attach_jsonl(metrics_env)
        elif trace_on or metrics_env == "1":
            metrics.default_registry().attach_jsonl(
                metrics.metrics_file_path(process_name, experiment,
                                          trial))
    except Exception as e:  # noqa: BLE001 - observability must never
        # prevent a worker from starting
        tracing.logger.warning(
            "Observability file export disabled for %s: %s",
            process_name, e)
