"""Metrics registry: counters, gauges, summaries, histograms.

The numbers half of the observability layer (docs/observability.md).
One :class:`MetricsRegistry` per process absorbs what used to be
scattered -- ``base/stats.py`` scalar side-channels, watchdog
liveness, serving queue depth/rejections, scheduler decode/evict/
hot-swap counters, checkpoint save/verify durations, elastic
degrade/rejoin events -- behind four metric types:

- ``Counter``: monotone totals (``..._total``).
- ``Gauge``: last-write-wins levels (queue depth, live workers).
- ``Summary``: count/sum/min/max/mean accumulation per label set
  (exec durations; the :class:`Accum` it is built on also backs the
  fixed ``base/stats.py`` export).
- ``Histogram``: bucketed observations in Prometheus ``le`` form.

Exports: :meth:`MetricsRegistry.to_prometheus` renders the standard
text exposition format (served from the worker health surface via the
``metrics`` worker command); :meth:`snapshot` returns a plain dict;
an attached JSONL sink (:meth:`attach_jsonl`) periodically persists
snapshots and immediately persists one-off structured records emitted
through :meth:`event` -- the structured replacement for the master's
free-form stats tables.

Label-aware convenience module functions (``inc``, ``set_gauge``,
``observe``, ``event``) operate on the process-default registry so
instrumentation call sites stay one line. All operations are cheap
and in-memory; file IO happens only in ``event``/``maybe_flush`` and
always outside the registry lock.
"""

import dataclasses
import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from realhf_tpu.base import logging

logger = logging.getLogger("obs.metrics")

METRICS_JSONL_ENV = "REALHF_TPU_METRICS_JSONL"
DEFAULT_SNAPSHOT_INTERVAL = 30.0


@dataclasses.dataclass
class Accum:
    """count/sum/min/max accumulator (mean derived). Also the engine
    behind the fixed ``base/stats.py`` export."""
    count: int = 0
    total: float = 0.0
    min: float = math.inf
    max: float = -math.inf

    def add(self, value: float):
        value = float(value)
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def as_dict(self) -> Dict[str, float]:
        if not self.count:
            return dict(count=0, sum=0.0, min=0.0, max=0.0, mean=0.0)
        return dict(count=self.count, sum=self.total, min=self.min,
                    max=self.max, mean=self.mean)


LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _prom_labels(key: LabelKey, extra: Sequence[Tuple[str, str]] = ()
                 ) -> str:
    items = list(key) + list(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{v}"' for k, v in items)
    return "{" + body + "}"


class _Metric:
    kind = ""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def prometheus_lines(self) -> List[str]:
        raise NotImplementedError

    def snapshot_value(self):
        raise NotImplementedError

    def _header(self) -> List[str]:
        out = []
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        return out


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels):
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def prometheus_lines(self) -> List[str]:
        with self._lock:
            values = dict(self._values)
        out = self._header()
        for key in sorted(values):
            out.append(f"{self.name}{_prom_labels(key)} "
                       f"{values[key]:g}")
        return out

    def snapshot_value(self):
        with self._lock:
            return {json.dumps(dict(k)) if k else "": v
                    for k, v in self._values.items()}


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels):
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def prometheus_lines(self) -> List[str]:
        with self._lock:
            values = dict(self._values)
        out = self._header()
        for key in sorted(values):
            out.append(f"{self.name}{_prom_labels(key)} "
                       f"{values[key]:g}")
        return out

    def snapshot_value(self):
        with self._lock:
            return {json.dumps(dict(k)) if k else "": v
                    for k, v in self._values.items()}


class Summary(_Metric):
    kind = "summary"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[LabelKey, Accum] = {}

    def observe(self, value: float, **labels):
        key = _label_key(labels)
        with self._lock:
            acc = self._values.get(key)
            if acc is None:
                acc = self._values[key] = Accum()
            acc.add(value)

    def accum(self, **labels) -> Accum:
        with self._lock:
            return dataclasses.replace(
                self._values.get(_label_key(labels), Accum()))

    def prometheus_lines(self) -> List[str]:
        with self._lock:
            values = {k: v.as_dict() for k, v in self._values.items()}
        out = self._header()
        for key in sorted(values):
            d = values[key]
            lbl = _prom_labels(key)
            out.append(f"{self.name}_count{lbl} {d['count']:g}")
            out.append(f"{self.name}_sum{lbl} {d['sum']:g}")
            out.append(f"{self.name}_min{lbl} {d['min']:g}")
            out.append(f"{self.name}_max{lbl} {d['max']:g}")
        return out

    def snapshot_value(self):
        with self._lock:
            return {json.dumps(dict(k)) if k else "": v.as_dict()
                    for k, v in self._values.items()}


#: default histogram buckets: wall-clock seconds from 1 ms to ~17 min
DEFAULT_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 2.5, 10.0, 60.0,
                   300.0, 1000.0)


def quantile_from_buckets(buckets: Sequence[float],
                          counts: Sequence[int], q: float,
                          observed_max: Optional[float] = None
                          ) -> Optional[float]:
    """Prometheus-style ``histogram_quantile``: linear interpolation
    inside the bucket the q-th observation falls into. ``counts`` is
    per-bucket (NOT cumulative), with the trailing overflow bucket --
    ``len(counts) == len(buckets) + 1``. A quantile landing in the
    overflow bucket returns ``observed_max`` when known, else the last
    finite bound (exactly Prometheus' behavior). None when empty."""
    total = sum(counts)
    if total <= 0:
        return None
    q = min(1.0, max(0.0, q))
    target = q * total
    cum = 0.0
    for i, le in enumerate(buckets):
        prev_cum = cum
        cum += counts[i]
        if cum >= target:
            lo = buckets[i - 1] if i > 0 else 0.0
            est = le if counts[i] == 0 \
                else lo + (le - lo) * (target - prev_cum) / counts[i]
            # interpolation can overshoot the largest observation
            # (the within-bucket distribution is unknown); when the
            # true max is known, no quantile can exceed it
            return min(est, observed_max) \
                if observed_max is not None else est
    return observed_max if observed_max is not None \
        else (buckets[-1] if buckets else None)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[LabelKey, List[int]] = {}
        self._accum: Dict[LabelKey, Accum] = {}

    def observe(self, value: float, **labels):
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
                self._accum[key] = Accum()
            for i, le in enumerate(self.buckets):
                if value <= le:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._accum[key].add(value)

    def prometheus_lines(self) -> List[str]:
        with self._lock:
            counts = {k: list(v) for k, v in self._counts.items()}
            accum = {k: v.as_dict() for k, v in self._accum.items()}
        out = self._header()
        for key in sorted(counts):
            cum = 0
            for i, le in enumerate(self.buckets):
                cum += counts[key][i]
                out.append(
                    f"{self.name}_bucket"
                    f"{_prom_labels(key, [('le', f'{le:g}')])} {cum}")
            cum += counts[key][-1]
            out.append(f"{self.name}_bucket"
                       f"{_prom_labels(key, [('le', '+Inf')])} {cum}")
            out.append(f"{self.name}_count{_prom_labels(key)} {cum}")
            out.append(f"{self.name}_sum{_prom_labels(key)} "
                       f"{accum[key]['sum']:g}")
        return out

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Estimated q-quantile for one label set (all observations
        when ``labels`` is empty and only one set exists -- otherwise
        the counts of every label set are merged)."""
        with self._lock:
            if labels:
                counts = self._counts.get(_label_key(labels))
                acc = self._accum.get(_label_key(labels))
                if counts is None:
                    return None
                counts = list(counts)
                observed_max = acc.max if acc and acc.count else None
            else:
                if not self._counts:
                    return None
                counts = [0] * (len(self.buckets) + 1)
                observed_max = None
                for k, v in self._counts.items():
                    for i, c in enumerate(v):
                        counts[i] += c
                    acc = self._accum[k]
                    if acc.count:
                        observed_max = acc.max \
                            if observed_max is None \
                            else max(observed_max, acc.max)
        return quantile_from_buckets(self.buckets, counts, q,
                                     observed_max=observed_max)

    def snapshot_value(self):
        with self._lock:
            return {json.dumps(dict(k)) if k else "": dict(
                        buckets=list(self.buckets), counts=list(v),
                        **self._accum[k].as_dict())
                    for k, v in self._counts.items()}


class MetricsRegistry:
    """Get-or-create metric store + exporters for one process."""

    def __init__(self, process_name: str = "proc"):
        self.process_name = process_name
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._jsonl_path: Optional[str] = None
        self._jsonl_interval = DEFAULT_SNAPSHOT_INTERVAL
        self._last_snapshot = 0.0
        self._io_lock = threading.Lock()

    # -- metric construction --------------------------------------------
    def _get(self, name: str, cls, **kwargs) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kwargs)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help=help)

    def summary(self, name: str, help: str = "") -> Summary:
        return self._get(name, Summary, help=help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get(name, Histogram, help=help, buckets=buckets)

    # -- one-line instrumentation ---------------------------------------
    def inc(self, name: str, amount: float = 1.0, **labels):
        self.counter(name).inc(amount, **labels)

    def set_gauge(self, name: str, value: float, **labels):
        self.gauge(name).set(value, **labels)

    def observe(self, name: str, value: float, **labels):
        self.summary(name).observe(value, **labels)

    def observe_hist(self, name: str, value: float, **labels):
        """Bucketed observation (quantile-capable; ``observe`` is the
        count/sum/min/max summary)."""
        self.histogram(name).observe(value, **labels)

    # -- exports ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            metrics = dict(self._metrics)
        return {name: dict(type=m.kind, values=m.snapshot_value())
                for name, m in sorted(metrics.items())}

    def to_prometheus(self) -> str:
        with self._lock:
            metrics = [m for _, m in sorted(self._metrics.items())]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.prometheus_lines())
        return "\n".join(lines) + ("\n" if lines else "")

    # -- JSONL sink ------------------------------------------------------
    def attach_jsonl(self, path: str,
                     interval: float = DEFAULT_SNAPSHOT_INTERVAL):
        """Periodic snapshot + immediate event persistence to ``path``
        (one JSON object per line). ``maybe_flush`` must be called
        from a poll loop for the periodic part."""
        self._jsonl_path = path
        self._jsonl_interval = interval
        self._last_snapshot = time.monotonic()

    def _write_line(self, record: Dict):
        path = self._jsonl_path
        if path is None:
            return
        line = json.dumps(record, default=str)
        with self._io_lock:
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "a") as f:
                    f.write(line + "\n")
            except OSError as e:  # metrics must never kill the run
                logger.warning("Metrics JSONL write to %s failed: %s",
                               path, e)

    def event(self, name: str, **fields) -> Dict:
        """Structured one-off record (the JSONL replacement for
        free-form log tables). Always returns the record; persists it
        when a JSONL sink is attached."""
        record = dict(ts=time.time(), kind="event", event=name,
                      process=self.process_name, **fields)
        self._write_line(record)
        return record

    def maybe_flush(self, now: Optional[float] = None):
        """Persist a snapshot when the interval elapsed (cheap no-op
        otherwise); call from worker poll loops."""
        if self._jsonl_path is None:
            return
        now = time.monotonic() if now is None else now
        if now - self._last_snapshot < self._jsonl_interval:
            return
        self._last_snapshot = now
        self._write_line(dict(ts=time.time(), kind="snapshot",
                              process=self.process_name,
                              metrics=self.snapshot()))

    def flush_final(self):
        """Unconditional final snapshot (marked ``final``) for clean
        exits: ``maybe_flush`` only fires on the interval, so a short
        run -- the inline runner, quickstart, a worker exiting between
        intervals -- would otherwise end with its last gauge values
        never persisted. Cheap no-op without a JSONL sink."""
        if self._jsonl_path is None:
            return
        self._last_snapshot = time.monotonic()
        self._write_line(dict(ts=time.time(), kind="snapshot",
                              final=True, process=self.process_name,
                              metrics=self.snapshot()))


# ----------------------------------------------------------------------
# Module-level default registry + convenience API.
# ----------------------------------------------------------------------
_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default


def reset_default():
    """Fresh default registry (test isolation)."""
    global _default
    _default = MetricsRegistry()


def inc(name: str, amount: float = 1.0, **labels):
    _default.inc(name, amount, **labels)


def set_gauge(name: str, value: float, **labels):
    _default.set_gauge(name, value, **labels)


def observe(name: str, value: float, **labels):
    _default.observe(name, value, **labels)


def observe_hist(name: str, value: float, **labels):
    _default.observe_hist(name, value, **labels)


def event(name: str, **fields) -> Dict:
    return _default.event(name, **fields)


def snapshot() -> Dict[str, Dict]:
    return _default.snapshot()


def to_prometheus() -> str:
    return _default.to_prometheus()


def maybe_flush():
    _default.maybe_flush()


def flush_final():
    _default.flush_final()


def metrics_file_path(process_name: str,
                      experiment: Optional[str] = None,
                      trial: Optional[str] = None) -> str:
    from realhf_tpu.base import constants
    safe = process_name.replace("/", "-").replace(" ", "_")
    return os.path.join(constants.run_log_path(experiment, trial),
                        "obs", "metrics", f"{safe}.metrics.jsonl")
