"""Structured span tracer with cross-process context propagation.

The timeline half of the observability layer (docs/observability.md):
nestable spans with trace/span ids and free-form attributes, buffered
lock-free per thread (each thread appends to its own list; drains
snapshot a length first so a racing append is never lost), and
exported as Chrome trace-event JSON that Perfetto / ``chrome://tracing``
load directly -- one PPO step renders as a single timeline across the
master, every model worker, and the serving fleet.

Propagation: a span's :class:`SpanContext` serializes to a plain dict
(``inject``) that rides in ``request_reply_stream.Payload.trace`` and
in the serving submit envelope; the receiving process ``extract``\\ s it
and parents its spans there, so causality survives process hops.

The tracer is OFF by default (every call is a cheap no-op). It turns
on either programmatically (:func:`configure`) or through the
``REALHF_TPU_TRACE=1`` env switch honored by every worker process,
the inline runner, and quickstart (:func:`configure_from_env`). When a
file path is configured, finished spans stream to it as JSON lines
(one Chrome event per line); :func:`merge_traces` folds every
per-process file of a run into one ``merged_trace.json``.
"""

import contextlib
import dataclasses
import json
import os
import threading
import time
import uuid
import zlib
from typing import Any, Dict, Iterator, List, Optional

from realhf_tpu.base import logging

logger = logging.getLogger("obs.tracing")

TRACE_ENV = "REALHF_TPU_TRACE"

#: file name of the per-run merged Chrome trace (Perfetto-loadable)
MERGED_TRACE_NAME = "merged_trace.json"


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """The propagatable identity of a span."""
    trace_id: str
    span_id: str

    def to_dict(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, d: Optional[Dict]) -> Optional["SpanContext"]:
        if not d or "trace_id" not in d or "span_id" not in d:
            return None
        return cls(trace_id=str(d["trace_id"]),
                   span_id=str(d["span_id"]))


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One timed operation. Create through :meth:`Tracer.span` (context
    manager, becomes the thread's current span) or
    :meth:`Tracer.start_span` (explicit lifetime for long-lived work
    like a serving request); ``finish()`` records it."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end", "attributes", "_tracer", "_finished")

    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional[SpanContext], attributes: Dict):
        self._tracer = tracer
        self.name = name
        self.trace_id = parent.trace_id if parent else _new_id()
        self.span_id = _new_id()
        self.parent_id = parent.span_id if parent else None
        self.start = time.time()
        self.end: Optional[float] = None
        self.attributes = dict(attributes)
        self._finished = False

    @property
    def context(self) -> SpanContext:
        return SpanContext(trace_id=self.trace_id, span_id=self.span_id)

    def set_attribute(self, key: str, value: Any):
        self.attributes[key] = value

    def finish(self, end_time: Optional[float] = None):
        if self._finished:
            return
        self._finished = True
        self.end = end_time if end_time is not None else time.time()
        self._tracer._record(self)


class _NoopSpan:
    """Returned while the tracer is disabled: every operation is free."""

    __slots__ = ()
    name = ""
    trace_id = span_id = parent_id = None
    attributes: Dict = {}
    context = None

    def set_attribute(self, key, value):
        pass

    def finish(self, end_time=None):
        pass


NOOP_SPAN = _NoopSpan()


class _ThreadBuffer(threading.local):
    """Per-thread finished-span buffer. Appends are thread-local (no
    lock); the drain snapshots a length first, so an append racing the
    drain lands past the snapshot and survives for the next drain."""

    def __init__(self, register):
        self.spans: List[Span] = []
        self.stack: List[Span] = []
        register(self.spans)


class Tracer:
    """Span factory + buffer + exporter for one logical process."""

    def __init__(self, process_name: str = "proc",
                 enabled: bool = False, path: Optional[str] = None):
        self.process_name = process_name
        self.enabled = enabled
        self.path = path
        self._buffers: List[List[Span]] = []
        self._buffers_lock = threading.Lock()
        self._file_lock = threading.Lock()
        self._wrote_meta = False
        self._tl = _ThreadBuffer(self._register_buffer)

    # -- configuration --------------------------------------------------
    def configure(self, process_name: Optional[str] = None,
                  enabled: Optional[bool] = None,
                  path: Optional[str] = None):
        if process_name is not None:
            self.process_name = process_name
            self._wrote_meta = False
        if enabled is not None:
            self.enabled = enabled
        if path is not None:
            self.path = path

    def _register_buffer(self, buf: List[Span]):
        with self._buffers_lock:
            self._buffers.append(buf)

    @property
    def pid(self) -> int:
        """Stable integer process id for Chrome events: derived from
        the process NAME, so a merged multi-process trace keeps one
        lane per worker and an in-process test harness can emulate
        several 'processes' with several tracers."""
        return zlib.crc32(self.process_name.encode()) & 0x7FFFFFFF

    # -- span creation --------------------------------------------------
    def current_span(self) -> Optional[Span]:
        stack = self._tl.stack
        return stack[-1] if stack else None

    def current_context(self) -> Optional[SpanContext]:
        cur = self.current_span()
        return cur.context if cur is not None else None

    def inject(self) -> Optional[Dict[str, str]]:
        """Current span context as a payload-ready dict (None when no
        span is open or tracing is off)."""
        ctx = self.current_context() if self.enabled else None
        return ctx.to_dict() if ctx is not None else None

    @staticmethod
    def extract(carrier: Optional[Dict]) -> Optional[SpanContext]:
        return SpanContext.from_dict(carrier)

    def start_span(self, name: str,
                   parent: Optional[SpanContext] = None,
                   **attributes) -> Span:
        """Explicit-lifetime span (NOT pushed on the thread's current
        stack): caller owns ``finish()``. ``parent=None`` parents to
        the thread's current span."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None:
            parent = self.current_context()
        return Span(self, name, parent, attributes)

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[SpanContext] = None,
             **attributes) -> Iterator[Span]:
        """Scoped span: becomes the thread's current span, so nested
        ``span()`` calls and ``inject()`` see it; finishes on exit
        (exceptions are recorded as an ``error`` attribute)."""
        if not self.enabled:
            yield NOOP_SPAN
            return
        sp = self.start_span(name, parent=parent, **attributes)
        self._tl.stack.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.set_attribute("error", repr(e))
            raise
        finally:
            stack = self._tl.stack
            if stack and stack[-1] is sp:
                stack.pop()
            sp.finish()

    # -- recording / export ---------------------------------------------
    def _record(self, span: Span):
        self._tl.spans.append(span)

    def drain(self) -> List[Span]:
        """Remove and return every finished span across all threads."""
        out: List[Span] = []
        with self._buffers_lock:
            buffers = list(self._buffers)
        for buf in buffers:
            n = len(buf)  # snapshot BEFORE slicing: racing appends
            out.extend(buf[:n])  # land at >= n and survive
            del buf[:n]
        return out

    def _event(self, span: Span) -> Dict:
        args = {k: v for k, v in span.attributes.items()}
        args["trace_id"] = span.trace_id
        args["span_id"] = span.span_id
        if span.parent_id:
            args["parent_id"] = span.parent_id
        return {
            "name": span.name, "ph": "X", "cat": "span",
            "ts": span.start * 1e6,
            "dur": max(0.0, (span.end or span.start) - span.start) * 1e6,
            "pid": self.pid, "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": args,
        }

    def _meta_events(self) -> List[Dict]:
        return [{"name": "process_name", "ph": "M", "pid": self.pid,
                 "args": {"name": self.process_name}}]

    def to_events(self, spans: List[Span],
                  with_meta: bool = True) -> List[Dict]:
        events = self._meta_events() if with_meta else []
        events.extend(self._event(s) for s in spans)
        return events

    def flush(self):
        """Drain buffered spans; when a file path is configured,
        append them to it as JSON lines. Serialization happens outside
        any span-recording path, so instrumented code never blocks on
        file IO."""
        spans = self.drain()
        if not spans or not self.path:
            return
        lines = [json.dumps(e, default=str)
                 for e in self.to_events(spans,
                                         with_meta=not self._wrote_meta)]
        payload = "\n".join(lines) + "\n"
        with self._file_lock:
            self._wrote_meta = True
            try:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(payload)
            except OSError as e:  # tracing must never kill the run
                logger.warning("Trace flush to %s failed: %s",
                               self.path, e)


# ----------------------------------------------------------------------
# Module-level default tracer (one per process) + convenience API.
# ----------------------------------------------------------------------
_default = Tracer()


def default_tracer() -> Tracer:
    return _default


def configure(process_name: Optional[str] = None,
              enabled: Optional[bool] = None,
              path: Optional[str] = None):
    _default.configure(process_name=process_name, enabled=enabled,
                       path=path)


def reset_default():
    """Fresh default tracer (test isolation)."""
    global _default
    _default = Tracer()


def enabled() -> bool:
    return _default.enabled


def span(name: str, parent: Optional[SpanContext] = None, **attributes):
    return _default.span(name, parent=parent, **attributes)


def start_span(name: str, parent: Optional[SpanContext] = None,
               **attributes) -> Span:
    return _default.start_span(name, parent=parent, **attributes)


def current_context() -> Optional[SpanContext]:
    return _default.current_context()


def inject() -> Optional[Dict[str, str]]:
    return _default.inject()


def extract(carrier: Optional[Dict]) -> Optional[SpanContext]:
    return Tracer.extract(carrier)


def flush():
    _default.flush()


def trace_env_enabled(env=None) -> bool:
    env = os.environ if env is None else env
    return env.get(TRACE_ENV, "") not in ("", "0")


def trace_dir(experiment: Optional[str] = None,
              trial: Optional[str] = None) -> str:
    from realhf_tpu.base import constants
    return os.path.join(constants.run_log_path(experiment, trial),
                        "obs", "trace")


def trace_file_path(process_name: str,
                    experiment: Optional[str] = None,
                    trial: Optional[str] = None) -> str:
    safe = process_name.replace("/", "-").replace(" ", "_")
    return os.path.join(trace_dir(experiment, trial),
                        f"{safe}.trace.jsonl")


def merge_traces(directory: Optional[str] = None,
                 out_path: Optional[str] = None,
                 experiment: Optional[str] = None,
                 trial: Optional[str] = None) -> Optional[str]:
    """Fold every per-process ``*.trace.jsonl`` under ``directory``
    (default: this run's trace dir) into one Chrome trace-event JSON
    (``merged_trace.json``). Returns the merged path, or None when
    there was nothing to merge. Unparseable lines are skipped -- a
    worker killed mid-write must not void everyone else's timeline."""
    directory = directory or trace_dir(experiment, trial)
    if not os.path.isdir(directory):
        return None
    events: List[Dict] = []
    for fn in sorted(os.listdir(directory)):
        if not fn.endswith(".trace.jsonl"):
            continue
        try:
            with open(os.path.join(directory, fn)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    if not events:
        return None
    out_path = out_path or os.path.join(directory, MERGED_TRACE_NAME)
    merged = {"traceEvents": events, "displayTimeUnit": "ms"}
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out_path)
    logger.info("Merged %d trace events from %s into %s.",
                len(events), directory, out_path)
    return out_path
