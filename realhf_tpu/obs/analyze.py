"""Trace-driven step-time attribution, goodput, and stragglers.

``merged_trace.json`` (and the per-process ``*.trace.jsonl`` shards it
is folded from) were write-only artifacts: a human could stare at the
Perfetto timeline, but nothing computed where a PPO step's wall-clock
actually went. This module reconstructs training steps from the span
tree the runtime already emits -- ``step`` roots, ``dispatch:<mfc>``
children in the master, ``mfc:<name>`` / ``data_fetch`` / ``realloc``
/ ``compute:<mfc>`` spans in the workers (cross-process parentage
rides in the span args) -- and answers the questions MegaScale-class
systems treat as table stakes (arXiv:2402.15627):

- **Per-step attribution**: each instant of the step window is
  assigned to exactly one of ``compute`` > ``data_fetch`` >
  ``realloc`` > ``dispatch`` (RPC/queueing overhead inside
  ``dispatch:*``/``mfc:*`` not covered by the finer categories) >
  ``idle``, by that priority, so the components SUM to the step wall.
- **Critical path**: the latest-finisher chain from the step root
  through ``dispatch:* -> mfc:* -> compute:*``, naming the bottleneck
  MFC of each step (and the modal bottleneck across steps).
- **Straggler skew**: per-worker busy seconds (union of that worker's
  compute/data_fetch/realloc spans) vs the median worker.
- **Goodput**: busy-compute seconds / step wall (union across
  workers), plus the per-worker normalized variant.

Entry points: :func:`analyze_path` (merged JSON, a ``.jsonl`` shard,
or a trace directory), :func:`analyze_events`,
:func:`format_report` (human table) and :func:`one_line_summary`
(the teardown log line next to the Perfetto pointer).
``scripts/analyze_trace.py`` is the CLI; ``bench.py`` embeds the same
report as its ``trace_report`` phase.
"""

import json
import os
from typing import Dict, List, Optional, Tuple

from realhf_tpu.base import logging

logger = logging.getLogger("obs.analyze")

#: attribution categories in claim-priority order (first match wins)
CATEGORIES = ("compute", "data_fetch", "realloc", "dispatch")

Interval = Tuple[float, float]


# ----------------------------------------------------------------------
# Interval algebra (all half-open [start, end) wall-clock seconds).
# ----------------------------------------------------------------------
def _merge(intervals: List[Interval]) -> List[Interval]:
    out: List[Interval] = []
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _measure(intervals: List[Interval]) -> float:
    return sum(e - s for s, e in intervals)


def _subtract(intervals: List[Interval],
              cover: List[Interval]) -> List[Interval]:
    """``intervals`` minus ``cover`` (both already merged/sorted)."""
    out: List[Interval] = []
    for s, e in intervals:
        cur = s
        for cs, ce in cover:
            if ce <= cur:
                continue
            if cs >= e:
                break
            if cs > cur:
                out.append((cur, cs))
            cur = max(cur, ce)
            if cur >= e:
                break
        if cur < e:
            out.append((cur, e))
    return out


def _clip(intervals: List[Interval], lo: float, hi: float
          ) -> List[Interval]:
    return [(max(s, lo), min(e, hi)) for s, e in intervals
            if min(e, hi) > max(s, lo)]


# ----------------------------------------------------------------------
# Loading.
# ----------------------------------------------------------------------
def load_events(path: str) -> List[Dict]:
    """Chrome trace events from a merged ``traceEvents`` JSON, a
    per-process ``.trace.jsonl`` shard (one event per line), or a
    directory of shards. Unparseable lines are skipped -- a worker
    killed mid-write must not void the analysis."""
    if os.path.isdir(path):
        events: List[Dict] = []
        for fn in sorted(os.listdir(path)):
            if fn.endswith(".trace.jsonl"):
                events.extend(load_events(os.path.join(path, fn)))
            elif fn == "merged_trace.json":
                events.extend(load_events(os.path.join(path, fn)))
        return events
    events = []
    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head == "{":
            try:
                doc = json.load(f)
                return list(doc.get("traceEvents", []))
            except ValueError:
                f.seek(0)  # fall through: maybe JSONL starting with {
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                continue
    return events


def _category(name: str) -> Optional[str]:
    if name.startswith("compute:"):
        return "compute"
    if name == "data_fetch" or name.startswith("data_fetch:"):
        return "data_fetch"
    if name == "realloc" or name.startswith("realloc:"):
        return "realloc"
    if name.startswith(("dispatch:", "mfc:", "rpc:")):
        return "dispatch"
    return None


def _mfc_of(event: Dict) -> Optional[str]:
    name = event.get("name", "")
    for prefix in ("dispatch:", "mfc:", "compute:"):
        if name.startswith(prefix):
            return name[len(prefix):]
    return event.get("args", {}).get("mfc")


# ----------------------------------------------------------------------
# Analysis.
# ----------------------------------------------------------------------
def analyze_events(events: List[Dict]) -> Dict:
    """The full report (module doc) from raw Chrome trace events."""
    pid_names = {e.get("pid"): e.get("args", {}).get("name")
                 for e in events
                 if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
    spans = [e for e in events if e.get("ph") == "X"]
    for e in spans:  # seconds once, up front (trace ts/dur are in us)
        e["_start"] = e.get("ts", 0.0) / 1e6
        e["_end"] = e["_start"] + e.get("dur", 0.0) / 1e6
    steps = sorted((e for e in spans if e.get("name") == "step"),
                   key=lambda e: e["_start"])
    if not steps:
        return dict(n_steps=0,
                    error="no `step` spans in trace (was the run "
                          "traced with REALHF_TPU_TRACE=1?)")
    by_trace: Dict[str, List[Dict]] = {}
    children: Dict[str, List[Dict]] = {}
    for e in spans:
        args = e.get("args", {})
        tid = args.get("trace_id")
        if tid is not None:
            by_trace.setdefault(tid, []).append(e)
        pid = args.get("parent_id")
        if pid is not None:
            children.setdefault(pid, []).append(e)

    def worker_of(e: Dict) -> str:
        w = e.get("args", {}).get("worker")
        if w:
            return str(w)
        return str(pid_names.get(e.get("pid"))
                   or f"pid:{e.get('pid')}")

    step_reports: List[Dict] = []
    totals = {c: 0.0 for c in CATEGORIES}
    totals["idle"] = 0.0
    total_wall = 0.0
    total_compute_union = 0.0
    worker_busy: Dict[str, float] = {}
    mfc_secs: Dict[str, float] = {}
    bottleneck_counts: Dict[str, int] = {}
    per_worker_ratio_num = per_worker_ratio_den = 0.0

    for idx, step in enumerate(steps):
        lo, hi = step["_start"], step["_end"]
        wall = hi - lo
        subtree = [e for e in by_trace.get(
            step.get("args", {}).get("trace_id"), [])
            if e is not step and e.get("name") != "step"]
        # intervals per category, claimed by priority so the
        # components sum exactly to the step wall
        attribution: Dict[str, float] = {}
        covered: List[Interval] = []
        for cat in CATEGORIES:
            ivs = _merge(_clip([(e["_start"], e["_end"])
                                for e in subtree
                                if _category(e.get("name", "")) == cat],
                               lo, hi))
            attribution[cat] = round(_measure(_subtract(ivs, covered)),
                                     9)
            covered = _merge(covered + ivs)
        attribution["idle"] = round(max(0.0, wall - _measure(covered)),
                                    9)
        compute_union = _measure(_merge(_clip(
            [(e["_start"], e["_end"]) for e in subtree
             if _category(e.get("name", "")) == "compute"], lo, hi)))

        # critical path: latest-finisher chain from the step root
        path: List[str] = []
        node = step
        seen = set()
        while True:
            sid = node.get("args", {}).get("span_id")
            if sid is None or sid in seen:
                break
            seen.add(sid)
            kids = children.get(sid, [])
            if not kids:
                break
            node = max(kids, key=lambda e: e["_end"])
            path.append(node.get("name", ""))
        bottleneck = next((m for m in (_mfc_of(dict(name=n))
                                       for n in path) if m), None)
        if bottleneck:
            bottleneck_counts[bottleneck] = \
                bottleneck_counts.get(bottleneck, 0) + 1

        # per-worker busy time (compute + data_fetch + realloc)
        busy_by_worker: Dict[str, List[Interval]] = {}
        for e in subtree:
            if _category(e.get("name", "")) in ("compute",
                                                "data_fetch",
                                                "realloc"):
                busy_by_worker.setdefault(worker_of(e), []).append(
                    (e["_start"], e["_end"]))
        step_workers = {w: round(_measure(_merge(_clip(iv, lo, hi))), 9)
                        for w, iv in busy_by_worker.items()}
        for w, b in step_workers.items():
            worker_busy[w] = worker_busy.get(w, 0.0) + b
        if step_workers:
            per_worker_ratio_num += sum(step_workers.values())
            per_worker_ratio_den += wall * len(step_workers)

        for e in subtree:
            if e.get("name", "").startswith("dispatch:"):
                mfc = _mfc_of(e)
                if mfc:
                    mfc_secs[mfc] = mfc_secs.get(mfc, 0.0) \
                        + (e["_end"] - e["_start"])
        if not any(n.startswith("dispatch:")
                   for n in (e.get("name", "") for e in subtree)):
            # inline mode: no master dispatch layer; mfc:* spans carry
            # the per-MFC walls instead
            for e in subtree:
                if e.get("name", "").startswith("mfc:"):
                    mfc = _mfc_of(e)
                    if mfc:
                        mfc_secs[mfc] = mfc_secs.get(mfc, 0.0) \
                            + (e["_end"] - e["_start"])

        args = step.get("args", {})
        step_reports.append(dict(
            step=idx,
            global_step=args.get("global_step"),
            batch_id=args.get("batch_id"),
            start=lo, wall_secs=round(wall, 9),
            attribution=attribution,
            critical_path=path,
            bottleneck_mfc=bottleneck,
            workers=step_workers))
        for c, v in attribution.items():
            totals[c] += v
        total_wall += wall
        total_compute_union += compute_union

    # modal bottleneck; dispatch-seconds break ties deterministically
    bottleneck_mfc = None
    if bottleneck_counts:
        bottleneck_mfc = max(
            bottleneck_counts,
            key=lambda m: (bottleneck_counts[m],
                           mfc_secs.get(m, 0.0), m))
    busy_values = sorted(worker_busy.values())
    median_busy = 0.0
    if busy_values:
        mid = len(busy_values) // 2
        median_busy = busy_values[mid] if len(busy_values) % 2 \
            else (busy_values[mid - 1] + busy_values[mid]) / 2
    stragglers = sorted(
        (dict(worker=w, busy_secs=round(b, 6),
              skew_vs_median_secs=round(b - median_busy, 6))
         for w, b in worker_busy.items()),
        key=lambda d: (-d["skew_vs_median_secs"], d["worker"]))

    return dict(
        n_steps=len(steps),
        wall_secs=round(total_wall, 6),
        attribution={c: round(v, 6) for c, v in totals.items()},
        attribution_frac={
            c: round(v / total_wall, 4) if total_wall else 0.0
            for c, v in totals.items()},
        goodput=round(total_compute_union / total_wall, 4)
        if total_wall else 0.0,
        goodput_per_worker=round(
            per_worker_ratio_num / per_worker_ratio_den, 4)
        if per_worker_ratio_den else None,
        bottleneck_mfc=bottleneck_mfc,
        bottleneck_counts=bottleneck_counts,
        mfc_secs={m: round(v, 6)
                  for m, v in sorted(mfc_secs.items())},
        stragglers=stragglers,
        steps=step_reports)


def analyze_path(path: str) -> Dict:
    return analyze_events(load_events(path))


# ----------------------------------------------------------------------
# Rendering.
# ----------------------------------------------------------------------
def format_report(report: Dict) -> str:
    """The human-readable report (docs/observability.md "Trace
    analytics" shows how to read it)."""
    if report.get("n_steps", 0) == 0:
        return f"trace report: {report.get('error', 'no steps')}"
    lines = [
        f"Trace report: {report['n_steps']} step(s), "
        f"{report['wall_secs']:.2f}s wall, "
        f"goodput {report['goodput']:.1%}"
        + (f" (per-worker {report['goodput_per_worker']:.1%})"
           if report.get("goodput_per_worker") is not None else ""),
        "",
        "  attribution          secs     frac",
    ]
    for cat in (*CATEGORIES, "idle"):
        lines.append(f"  {cat:<16} {report['attribution'][cat]:>9.3f}"
                     f"  {report['attribution_frac'][cat]:>6.1%}")
    if report.get("bottleneck_mfc"):
        counts = report.get("bottleneck_counts", {})
        lines += ["", f"  critical-path MFC: "
                      f"{report['bottleneck_mfc']} "
                      f"(bottleneck in "
                      f"{counts.get(report['bottleneck_mfc'], 0)}"
                      f"/{report['n_steps']} steps)"]
    if report.get("mfc_secs"):
        lines += ["", "  per-MFC wall (dispatch spans):"]
        for mfc, secs in sorted(report["mfc_secs"].items(),
                                key=lambda kv: -kv[1]):
            lines.append(f"    {mfc:<24} {secs:>9.3f}s")
    if report.get("stragglers"):
        lines += ["", "  worker busy-time skew vs median:"]
        for s in report["stragglers"]:
            lines.append(f"    {s['worker']:<24} "
                         f"{s['busy_secs']:>9.3f}s  "
                         f"{s['skew_vs_median_secs']:>+8.3f}s")
    return "\n".join(lines)


def one_line_summary(report: Dict) -> str:
    if report.get("n_steps", 0) == 0:
        return f"trace report: {report.get('error', 'no steps')}"
    parts = [f"{report['n_steps']} steps",
             f"goodput {report['goodput']:.0%}"]
    if report.get("bottleneck_mfc"):
        parts.append(f"bottleneck MFC {report['bottleneck_mfc']}")
    stragglers = report.get("stragglers") or []
    if len(stragglers) > 1 \
            and stragglers[0]["skew_vs_median_secs"] > 0:
        parts.append(f"straggler {stragglers[0]['worker']} "
                     f"(+{stragglers[0]['skew_vs_median_secs']:.2f}s "
                     "vs median)")
    return "trace report: " + ", ".join(parts)


def summarize_path(path: Optional[str]) -> Optional[str]:
    """One-line summary of a trace file for teardown logs; never
    raises (teardown must not mask the trial's outcome)."""
    if not path:
        return None
    try:
        return one_line_summary(analyze_path(path))
    except Exception as e:  # noqa: BLE001
        logger.debug("Trace summary of %s failed: %s", path, e)
        return None
