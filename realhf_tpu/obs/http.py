"""Live HTTP telemetry endpoints: the scrape side of the obs layer.

Before this module the metrics registry was only reachable through
the ZMQ ``metrics`` worker command and the pod controller's
``file_sd`` output pointed Prometheus at ports nothing listened on.
:class:`TelemetryServer` closes the loop: a stdlib
``ThreadingHTTPServer`` (no new dependencies) that every worker
process and the inline runner start on an ephemeral port, publishing
the address under ``names.telemetry`` so the pod controller can
resolve real per-worker scrape targets (``system/pod.py``).

Endpoints (docs/observability.md "Scraping the fleet"):

- ``GET /metrics``  -- Prometheus text exposition of the process
  default :class:`~realhf_tpu.obs.metrics.MetricsRegistry`.
- ``GET /healthz``  -- worker liveness JSON (status, heartbeat age,
  lease/epoch state); HTTP 200 while serving, 503 once draining /
  preempted / errored, so a probing LB stops sending traffic the
  moment a drain starts.
- ``GET /flight``   -- the flight-recorder ring as JSON (a live
  postmortem preview; the on-crash dump is still the durable copy).
- ``GET /statusz``  -- one-page process status: metrics snapshot,
  trace configuration, flight-ring size.

Serving a scrape never touches worker state beyond snapshotting it;
handlers render under no registry lock (the registry snapshots
internally) and errors return 500 without taking the process down.
The server is ON by default (it binds an ephemeral port and costs one
daemon thread); ``REALHF_TPU_TELEMETRY=0`` opts out,
``REALHF_TPU_TELEMETRY_PORT`` pins the port.

:func:`parse_prometheus_text` is the matching reader: it parses the
exposition format back into ``name -> [(labels, value)]`` so the
``run_serve`` autoscaler can consume a router's ``/metrics`` over
HTTP exactly as a real Prometheus would.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from realhf_tpu.base import logging
from realhf_tpu.obs import flight, metrics, tracing

logger = logging.getLogger("obs.http")

TELEMETRY_ENV = "REALHF_TPU_TELEMETRY"
TELEMETRY_PORT_ENV = "REALHF_TPU_TELEMETRY_PORT"

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: per-connection socket timeout: a scraper that connects and then
#: stalls (or trickles bytes) must not pin a handler thread forever.
#: ``BaseHTTPRequestHandler`` honors the class attribute by calling
#: ``settimeout`` on the connection.
REQUEST_TIMEOUT_SECS = 30.0
#: request-line / total-header byte bounds, far below the stdlib's
#: 64 KiB-per-line / 100-header ceilings: telemetry requests are tiny
#: (``GET /metrics``), so anything larger is garbage or abuse.
MAX_REQUEST_LINE_BYTES = 8192
MAX_HEADER_BYTES = 16384


class BoundedRequestHandler(BaseHTTPRequestHandler):
    """A ``BaseHTTPRequestHandler`` hardened for unattended serving:
    per-connection timeout, bounded request line, bounded total header
    bytes. Shared by the telemetry endpoints here and the serving
    gateway (``serving/gateway.py``) -- both sit on the same stdlib
    HTTP plane and face the same stalled/abusive-client hazards."""

    timeout = REQUEST_TIMEOUT_SECS
    max_request_line = MAX_REQUEST_LINE_BYTES
    max_header_bytes = MAX_HEADER_BYTES

    def handle_one_request(self):
        """Stdlib flow with tighter bounds: 414 on an oversized
        request line, 431 on oversized headers, connection close on a
        read timeout (the stalled-scraper case)."""
        try:
            self.raw_requestline = self.rfile.readline(
                self.max_request_line + 1)
            if len(self.raw_requestline) > self.max_request_line:
                self.requestline = ""
                self.request_version = ""
                self.command = ""
                self.send_error(414)
                self.close_connection = True
                return
            if not self.raw_requestline:
                self.close_connection = True
                return
            if not self.parse_request():
                return  # parse_request already sent the error
            header_bytes = sum(len(k) + len(v) + 4
                               for k, v in self.headers.items())
            if header_bytes > self.max_header_bytes:
                self.send_error(431)
                self.close_connection = True
                return
            mname = "do_" + self.command
            if not hasattr(self, mname):
                self.send_error(
                    501, f"Unsupported method ({self.command!r})")
                return
            getattr(self, mname)()
            self.wfile.flush()
        except TimeoutError as e:
            self.log_error("request timed out: %r", e)
            self.close_connection = True

#: health states that answer 200 (anything else -- draining,
#: preempted, error, unknown -- answers 503 so probers back off)
HEALTHY_STATES = ("READY", "RUNNING", "PAUSED")


def telemetry_env_enabled(env=None) -> bool:
    import os
    env = os.environ if env is None else env
    return env.get(TELEMETRY_ENV, "1") not in ("0", "off", "false")


class TelemetryServer:
    """One process's HTTP telemetry surface (module doc).

    ``health`` is a zero-arg callable returning the ``/healthz`` JSON
    dict; its ``"state"`` key decides the HTTP status (200 for
    :data:`HEALTHY_STATES`, 503 otherwise). Provider exceptions render
    as ``state="error"`` -- a scrape must never take the worker down.
    """

    def __init__(self, process_name: str = "proc", *,
                 port: int = 0, host: str = "",
                 registry: Optional[metrics.MetricsRegistry] = None,
                 recorder: Optional[flight.FlightRecorder] = None,
                 tracer: Optional[tracing.Tracer] = None,
                 health: Optional[Callable[[], Dict]] = None):
        self.process_name = process_name
        self._registry = registry
        self._recorder = recorder
        self._tracer = tracer
        self._health = health
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._requested_port = port
        self._host = host

    # late binding: tests swap the process defaults per test, so the
    # server must read them at scrape time, not construction time
    @property
    def registry(self) -> metrics.MetricsRegistry:
        return self._registry or metrics.default_registry()

    @property
    def recorder(self) -> flight.FlightRecorder:
        return self._recorder or flight.default_recorder()

    @property
    def tracer(self) -> tracing.Tracer:
        return self._tracer or tracing.default_tracer()

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "TelemetryServer":
        server = self

        class Handler(BoundedRequestHandler):
            # scrapes at 1-15s cadence would otherwise spam the log
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                try:
                    server._route(self)
                except BrokenPipeError:
                    pass  # scraper hung up mid-response
                except Exception as e:  # noqa: BLE001 - a scrape must
                    # never kill the serving thread
                    try:
                        server._respond(self, 500, "text/plain",
                                        f"internal error: {e!r}\n"
                                        .encode())
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"telemetry[{self.process_name}]", daemon=True)
        self._thread.start()
        logger.info("Telemetry endpoints for %s on port %d "
                    "(/metrics /healthz /flight /statusz).",
                    self.process_name, self.port)
        return self

    @property
    def port(self) -> int:
        if self._httpd is None:
            return 0
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        """``host:port`` as published under ``names.telemetry`` (the
        advertised host is this box's routable IP, not the bind
        wildcard)."""
        from realhf_tpu.base import network
        return f"{network.gethostip()}:{self.port}"

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()

    # -- routing --------------------------------------------------------
    def _respond(self, handler, code: int, content_type: str,
                 body: bytes):
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _json(self, handler, payload: Dict, code: int = 200):
        self._respond(handler, code, "application/json",
                      (json.dumps(payload, default=str) + "\n")
                      .encode())

    def _route(self, handler):
        path = handler.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            self._respond(handler, 200, PROM_CONTENT_TYPE,
                          self.registry.to_prometheus().encode())
        elif path == "/healthz":
            health = self.health_snapshot()
            state = str(health.get("state", "UNKNOWN"))
            code = 200 if state in HEALTHY_STATES else 503
            self._json(handler, health, code=code)
        elif path == "/flight":
            events = self.recorder.events()
            self._json(handler, dict(worker=self.recorder.name,
                                     n_events=len(events),
                                     events=events))
        elif path == "/statusz":
            tracer = self.tracer
            self._json(handler, dict(
                process=self.process_name,
                time=time.time(),
                health=self.health_snapshot(),
                trace=dict(enabled=tracer.enabled, path=tracer.path),
                flight_events=len(self.recorder),
                metrics=self.registry.snapshot()))
        else:
            self._respond(handler, 404, "text/plain",
                          b"unknown path (have: /metrics /healthz "
                          b"/flight /statusz)\n")

    def health_snapshot(self) -> Dict:
        if self._health is None:
            return dict(state="RUNNING", process=self.process_name)
        try:
            return dict(self._health())
        except Exception as e:  # noqa: BLE001 - provider bugs must
            # surface as an unhealthy answer, not a dead endpoint
            return dict(state="error", error=repr(e),
                        process=self.process_name)


# ----------------------------------------------------------------------
# Process-default server (one per worker / inline runner).
# ----------------------------------------------------------------------
_default: Optional[TelemetryServer] = None


def default_server() -> Optional[TelemetryServer]:
    return _default


def start_from_env(process_name: str,
                   health: Optional[Callable[[], Dict]] = None
                   ) -> Optional[TelemetryServer]:
    """Start this process's telemetry endpoints per the env (module
    doc): returns the running server, or None when opted out
    (``REALHF_TPU_TELEMETRY=0``) or the bind failed. Never raises --
    observability must not take a worker down."""
    global _default
    import os
    if not telemetry_env_enabled():
        return None
    try:
        port = int(os.environ.get(TELEMETRY_PORT_ENV, "0") or 0)
        server = TelemetryServer(process_name, port=port,
                                 health=health).start()
    except Exception as e:  # noqa: BLE001
        logger.warning("Telemetry endpoints disabled for %s: %s",
                       process_name, e)
        return None
    _default = server
    return server


def stop_default():
    global _default
    server, _default = _default, None
    if server is not None:
        server.stop()


# ----------------------------------------------------------------------
# Prometheus text parsing (the consumer side of /metrics).
# ----------------------------------------------------------------------
def _parse_labels(body: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        eq = body.index("=", i)
        key = body[i:eq].strip().strip(",")
        j = body.index('"', eq) + 1
        val = []
        while j < n and body[j] != '"':
            if body[j] == "\\" and j + 1 < n:
                j += 1
            val.append(body[j])
            j += 1
        out[key] = "".join(val)
        i = j + 1
    return out


def parse_prometheus_text(text: str
                          ) -> Dict[str, List[Tuple[Dict[str, str],
                                                    float]]]:
    """Parse the exposition format into
    ``name -> [(labels, value), ...]``. Histogram/summary series keep
    their ``_bucket``/``_count``/``_sum`` suffixes as distinct names
    (exactly how Prometheus stores them). Malformed lines are skipped
    -- a half-written scrape must not fail the consumer."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                labels_body, value_part = rest.rsplit("}", 1)
                labels = _parse_labels(labels_body)
            else:
                name, value_part = line.split(None, 1)
                labels = {}
            value = float(value_part.split()[0])
        except (ValueError, IndexError):
            continue
        out.setdefault(name.strip(), []).append((labels, value))
    return out


def prom_scalar(families: Dict[str, List[Tuple[Dict[str, str], float]]],
                name: str, default: float = 0.0, *,
                agg: str = "sum") -> float:
    """One number for a family: ``sum`` across label sets (counters)
    or ``last`` (single-series gauges)."""
    series = families.get(name)
    if not series:
        return default
    if agg == "last":
        return series[-1][1]
    return sum(v for _, v in series)


def prom_histogram_quantile(
        families: Dict[str, List[Tuple[Dict[str, str], float]]],
        name: str, q: float) -> Optional[float]:
    """``histogram_quantile(q, ...)`` over a scraped histogram family:
    merges every ``{name}_bucket`` series (summing counts per ``le``)
    and interpolates, i.e. the fleet-wide quantile estimate a real
    Prometheus would compute."""
    buckets = families.get(f"{name}_bucket")
    if not buckets:
        return None
    by_le: Dict[float, float] = {}
    for labels, value in buckets:
        le = labels.get("le", "")
        bound = float("inf") if le in ("+Inf", "inf") else float(le)
        by_le[bound] = by_le.get(bound, 0.0) + value
    pairs = sorted(by_le.items())
    bounds = [b for b, _ in pairs if b != float("inf")]
    cum = [c for _, c in pairs]
    counts = [cum[0]] + [cum[i] - cum[i - 1]
                         for i in range(1, len(cum))]
    return metrics.quantile_from_buckets(bounds, counts, q)
