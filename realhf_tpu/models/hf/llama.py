"""LLaMA-family HF conversion (llama, and the llama-likes qwen2 and
mistral which differ only in bias/window flags).

Parity with reference ``realhf/api/from_hf/llama.py:19-271`` /
``qwen2.py`` / ``mistral.py``.
"""

from typing import Any, Dict

import numpy as np

from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.models.hf.registry import (
    HFFamily,
    StateDict,
    register_hf_family,
    stack_layers,
    unstack_layers,
)


def _config_from_hf_llama(d: Dict[str, Any], is_critic: bool,
                          attention_bias_default: bool = False
                          ) -> TransformerConfig:
    nq = d["num_attention_heads"]
    hidden = d["hidden_size"]
    return TransformerConfig(
        n_layers=d["num_hidden_layers"],
        n_kv_heads=d.get("num_key_value_heads", nq),
        n_q_heads=nq,
        hidden_dim=hidden,
        head_dim=d.get("head_dim") or hidden // nq,
        intermediate_dim=d["intermediate_size"],
        vocab_size=d["vocab_size"],
        n_positions=d.get("max_position_embeddings"),
        layer_norm_epsilon=d.get("rms_norm_eps", 1e-6),
        activation_function="silu",
        use_attention_bias=d.get("attention_bias", attention_bias_default),
        use_attn_proj_bias=False,
        use_mlp_bias=False,
        layer_norm_type="rms",
        mlp_type="llama",
        apply_rotary=True,
        rotary_base=d.get("rope_theta", 10000.0),
        scale_attn_by_inverse_layer_idx=False,
        tied_embedding=d.get("tie_word_embeddings", False),
        sliding_window=d.get("sliding_window"),
        is_critic=is_critic,
    )


def _config_to_hf_llama(cfg: TransformerConfig,
                        model_type: str = "llama") -> Dict[str, Any]:
    d = {
        "model_type": model_type,
        "architectures": [{"llama": "LlamaForCausalLM",
                           "qwen2": "Qwen2ForCausalLM",
                           "mistral": "MistralForCausalLM"}[model_type]],
        "hidden_size": cfg.hidden_dim,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.intermediate_dim,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_q_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.n_positions or 4096,
        "rms_norm_eps": cfg.layer_norm_epsilon,
        "rope_theta": cfg.rotary_base,
        "tie_word_embeddings": cfg.tied_embedding,
        "hidden_act": "silu",
        "torch_dtype": "float32",
    }
    if model_type == "llama":
        d["attention_bias"] = cfg.use_attention_bias
    if cfg.sliding_window is not None:
        d["sliding_window"] = cfg.sliding_window
    return d


_PRE = "model.layers.{}."


def llama_backbone_from_hf(state: StateDict,
                           cfg: TransformerConfig) -> Dict[str, Any]:
    """Embedding + attention + norms + head shared by every
    llama-attention family (llama/qwen2/mistral/gemma/mixtral);
    ``blocks.mlp`` is left for the family converter to fill."""
    nl = cfg.n_layers
    params: Dict[str, Any] = {
        "embed": {"wte": state["model.embed_tokens.weight"]},
        "blocks": {
            "ln1": {"scale": stack_layers(
                state, _PRE + "input_layernorm.weight", nl)},
            "attn": {
                "wq": stack_layers(state, _PRE + "self_attn.q_proj.weight",
                                   nl, transpose=True),
                "wk": stack_layers(state, _PRE + "self_attn.k_proj.weight",
                                   nl, transpose=True),
                "wv": stack_layers(state, _PRE + "self_attn.v_proj.weight",
                                   nl, transpose=True),
                "wo": stack_layers(state, _PRE + "self_attn.o_proj.weight",
                                   nl, transpose=True),
            },
            "ln2": {"scale": stack_layers(
                state, _PRE + "post_attention_layernorm.weight", nl)},
            "mlp": {},
        },
        "ln_f": {"scale": state["model.norm.weight"]},
    }
    if cfg.use_attention_bias:
        a = params["blocks"]["attn"]
        a["bq"] = stack_layers(state, _PRE + "self_attn.q_proj.bias", nl)
        a["bk"] = stack_layers(state, _PRE + "self_attn.k_proj.bias", nl)
        a["bv"] = stack_layers(state, _PRE + "self_attn.v_proj.bias", nl)
    if not cfg.is_critic and not cfg.tied_embedding:
        params["head"] = {"w": state["lm_head.weight"].T.copy()}
    return params


def llama_backbone_to_hf(params: Dict[str, Any], cfg: TransformerConfig,
                         out: StateDict):
    out["model.embed_tokens.weight"] = np.ascontiguousarray(
        params["embed"]["wte"])
    b = params["blocks"]
    unstack_layers(b["ln1"]["scale"], _PRE + "input_layernorm.weight", out)
    unstack_layers(b["attn"]["wq"], _PRE + "self_attn.q_proj.weight", out,
                   transpose=True)
    unstack_layers(b["attn"]["wk"], _PRE + "self_attn.k_proj.weight", out,
                   transpose=True)
    unstack_layers(b["attn"]["wv"], _PRE + "self_attn.v_proj.weight", out,
                   transpose=True)
    unstack_layers(b["attn"]["wo"], _PRE + "self_attn.o_proj.weight", out,
                   transpose=True)
    unstack_layers(b["ln2"]["scale"],
                   _PRE + "post_attention_layernorm.weight", out)
    if cfg.use_attention_bias:
        unstack_layers(b["attn"]["bq"], _PRE + "self_attn.q_proj.bias", out)
        unstack_layers(b["attn"]["bk"], _PRE + "self_attn.k_proj.bias", out)
        unstack_layers(b["attn"]["bv"], _PRE + "self_attn.v_proj.bias", out)
    out["model.norm.weight"] = np.ascontiguousarray(params["ln_f"]["scale"])
    if not cfg.is_critic and not cfg.tied_embedding:
        out["lm_head.weight"] = np.ascontiguousarray(params["head"]["w"].T)


def _params_from_hf_llama(state: StateDict,
                          cfg: TransformerConfig) -> Dict[str, Any]:
    params = llama_backbone_from_hf(state, cfg)
    nl = cfg.n_layers
    params["blocks"]["mlp"] = {
        "wg": stack_layers(state, _PRE + "mlp.gate_proj.weight", nl,
                           transpose=True),
        "wu": stack_layers(state, _PRE + "mlp.up_proj.weight", nl,
                           transpose=True),
        "wd": stack_layers(state, _PRE + "mlp.down_proj.weight", nl,
                           transpose=True),
    }
    return params


def _params_to_hf_llama(params: Dict[str, Any],
                        cfg: TransformerConfig) -> StateDict:
    out: StateDict = {}
    llama_backbone_to_hf(params, cfg, out)
    b = params["blocks"]
    unstack_layers(b["mlp"]["wg"], _PRE + "mlp.gate_proj.weight", out,
                   transpose=True)
    unstack_layers(b["mlp"]["wu"], _PRE + "mlp.up_proj.weight", out,
                   transpose=True)
    unstack_layers(b["mlp"]["wd"], _PRE + "mlp.down_proj.weight", out,
                   transpose=True)
    return out


register_hf_family(HFFamily(
    name="llama", hf_model_type="llama",
    config_from_hf=_config_from_hf_llama,
    config_to_hf=lambda cfg: _config_to_hf_llama(cfg, "llama"),
    params_from_hf=_params_from_hf_llama,
    params_to_hf=_params_to_hf_llama,
))

register_hf_family(HFFamily(
    name="qwen2", hf_model_type="qwen2",
    # qwen2 always uses qkv bias; its HF config has no attention_bias key.
    config_from_hf=lambda d, crit: _config_from_hf_llama(
        d, crit, attention_bias_default=True),
    config_to_hf=lambda cfg: _config_to_hf_llama(cfg, "qwen2"),
    params_from_hf=_params_from_hf_llama,
    params_to_hf=_params_to_hf_llama,
))

register_hf_family(HFFamily(
    name="mistral", hf_model_type="mistral",
    config_from_hf=lambda d, crit: _config_from_hf_llama(d, crit),
    config_to_hf=lambda cfg: _config_to_hf_llama(cfg, "mistral"),
    params_from_hf=_params_from_hf_llama,
    params_to_hf=_params_to_hf_llama,
))
