"""Gemma HF conversion (reference ``realhf/api/from_hf/gemma.py``):
gemma-style RMSNorm (1 + scale), normalized embeddings, tied LM head,
gelu_tanh activation, head_dim decoupled from hidden/nq.
"""

from typing import Any, Dict

from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.models.hf.llama import (
    _params_from_hf_llama,
    _params_to_hf_llama,
)
from realhf_tpu.models.hf.registry import HFFamily, register_hf_family


def _config_from_hf(d: Dict[str, Any], is_critic: bool) -> TransformerConfig:
    nq = d["num_attention_heads"]
    return TransformerConfig(
        n_layers=d["num_hidden_layers"],
        n_kv_heads=d.get("num_key_value_heads", nq),
        n_q_heads=nq,
        hidden_dim=d["hidden_size"],
        head_dim=d.get("head_dim", 256),
        intermediate_dim=d["intermediate_size"],
        vocab_size=d["vocab_size"],
        n_positions=d.get("max_position_embeddings"),
        layer_norm_epsilon=d.get("rms_norm_eps", 1e-6),
        activation_function="gelu_new",
        use_attention_bias=d.get("attention_bias", False),
        use_attn_proj_bias=False,
        use_mlp_bias=False,
        layer_norm_type="gemma",
        mlp_type="llama",
        apply_rotary=True,
        rotary_base=d.get("rope_theta", 10000.0),
        scale_attn_by_inverse_layer_idx=False,
        normalize_embed=True,
        tied_embedding=True,
        is_critic=is_critic,
    )


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    return {
        "model_type": "gemma",
        "architectures": ["GemmaForCausalLM"],
        "hidden_size": cfg.hidden_dim,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.intermediate_dim,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_q_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.n_positions or 8192,
        "rms_norm_eps": cfg.layer_norm_epsilon,
        "rope_theta": cfg.rotary_base,
        "hidden_act": "gelu_pytorch_tanh",
        "hidden_activation": "gelu_pytorch_tanh",
        "tie_word_embeddings": True,
        "attention_bias": cfg.use_attention_bias,
        "torch_dtype": "float32",
    }


register_hf_family(HFFamily(
    name="gemma", hf_model_type="gemma",
    config_from_hf=_config_from_hf,
    config_to_hf=_config_to_hf,
    params_from_hf=_params_from_hf_llama,
    params_to_hf=_params_to_hf_llama,
))
