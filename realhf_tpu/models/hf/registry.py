"""Registry of HuggingFace model-family converters.

Parity with reference ``realhf/impl/model/conversion/hf_registry.py``
(HFModelRegistry:25): each family supplies config and weight mappings
in both directions; checkpoints are HF-compatible safetensors with an
index json, so actors trained here load directly into HF/vLLM
(reference ``docs/source/arch.rst:118-127``). Critic value heads are
stored as an extra ``value_head.safetensors`` alongside the HF layout
(the reference likewise uses a ReaL-only critic format).

Weights convert between the framework's stacked-layer pytree
(layer-stacked arrays, transformer.py) and HF's per-layer (out, in)
torch convention.
"""

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Optional

import numpy as np

from realhf_tpu.base import logging
from realhf_tpu.models.config import TransformerConfig

logger = logging.getLogger("hf_registry")

StateDict = Dict[str, np.ndarray]


@dataclasses.dataclass
class HFFamily:
    name: str
    hf_model_type: str
    # TransformerConfig <-> HF config dict (kwargs of the HF config class)
    config_from_hf: Callable[[Dict[str, Any], bool], TransformerConfig]
    config_to_hf: Callable[[TransformerConfig], Dict[str, Any]]
    # stacked pytree <-> HF flat state dict of numpy arrays
    params_from_hf: Callable[[StateDict, TransformerConfig], Dict[str, Any]]
    params_to_hf: Callable[[Dict[str, Any], TransformerConfig], StateDict]


HF_FAMILIES: Dict[str, HFFamily] = {}


def register_hf_family(family: HFFamily):
    if family.name in HF_FAMILIES:
        raise ValueError(f"HF family {family.name} already registered.")
    HF_FAMILIES[family.name] = family


def config_from_hf(family: str, hf_config: Any,
                   is_critic: bool = False) -> TransformerConfig:
    d = hf_config if isinstance(hf_config, dict) else hf_config.to_dict()
    return HF_FAMILIES[family].config_from_hf(d, is_critic)


def config_to_hf(family: str, cfg: TransformerConfig) -> Dict[str, Any]:
    return HF_FAMILIES[family].config_to_hf(cfg)


def params_from_hf(family: str, state_dict: StateDict,
                   cfg: TransformerConfig) -> Dict[str, Any]:
    return HF_FAMILIES[family].params_from_hf(state_dict, cfg)


def params_to_hf(family: str, params: Dict[str, Any],
                 cfg: TransformerConfig) -> StateDict:
    return HF_FAMILIES[family].params_to_hf(params, cfg)


# ----------------------------------------------------------------------
# Checkpoint IO (sharded safetensors + index, reference hf_registry
# save:201 / load:62 + base/saveload_utils.py:14)
# ----------------------------------------------------------------------
_INDEX_NAME = "model.safetensors.index.json"
_VALUE_HEAD_NAME = "value_head.safetensors"
_SHARD_SIZE = 2 * 1024 ** 3  # bytes per safetensors shard


def detect_family(path: str) -> str:
    with open(os.path.join(path, "config.json")) as f:
        mt = json.load(f)["model_type"]
    for fam in HF_FAMILIES.values():
        if fam.hf_model_type == mt:
            return fam.name
    raise ValueError(f"No registered family for HF model_type={mt}")


def load_hf_checkpoint(path: str, family: Optional[str] = None,
                       is_critic: bool = False):
    """Read an HF-layout directory -> (TransformerConfig, params pytree).

    All shards are materialized in host RAM, then device_put with the
    target sharding does the placement. (The reference instead reads
    only the shards each rank needs, hf_registry.load:62; a streaming
    per-host loader is a planned optimization for >host-RAM models.)
    """
    import safetensors.numpy

    family = family or detect_family(path)
    with open(os.path.join(path, "config.json")) as f:
        hf_config = json.load(f)
    cfg = config_from_hf(family, hf_config, is_critic=is_critic)

    state: StateDict = {}
    index_path = os.path.join(path, _INDEX_NAME)
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        shards = sorted(set(index["weight_map"].values()))
        for shard in shards:
            state.update(safetensors.numpy.load_file(os.path.join(path, shard)))
    else:
        state.update(safetensors.numpy.load_file(
            os.path.join(path, "model.safetensors")))
    params = params_from_hf(family, state, cfg)

    vh_path = os.path.join(path, _VALUE_HEAD_NAME)
    if is_critic:
        if os.path.exists(vh_path):
            vh = safetensors.numpy.load_file(vh_path)
            params["head"] = {"w": vh["value_head.weight"]}
        else:
            # init_critic_from_actor: drop the LM head, fresh value head
            # (reference model_api.py / hf_registry load path).
            rng = np.random.RandomState(0)
            params["head"] = {"w": rng.normal(
                0, 0.02, size=(cfg.hidden_dim, 1)).astype(np.float32)}
            logger.info("Initialized critic value head from scratch.")
    return cfg, params


class _LazyShardState:
    """Dict-like view over a sharded safetensors checkpoint that reads
    ONE tensor at a time (``safetensors.safe_open``), so host memory
    never holds a full shard, let alone the full model."""

    def __init__(self, path: str):
        self._path = path
        index_path = os.path.join(path, _INDEX_NAME)
        if os.path.exists(index_path):
            with open(index_path) as f:
                self._weight_map = json.load(f)["weight_map"]
        else:
            import safetensors

            fname = "model.safetensors"
            with safetensors.safe_open(os.path.join(path, fname),
                                       framework="np") as f:
                self._weight_map = {k: fname for k in f.keys()}
        self._handles: Dict[str, Any] = {}

    def _handle(self, fname: str):
        if fname not in self._handles:
            import safetensors
            self._handles[fname] = safetensors.safe_open(
                os.path.join(self._path, fname), framework="np")
        return self._handles[fname]

    def __contains__(self, key: str) -> bool:
        return key in self._weight_map

    def __getitem__(self, key: str) -> np.ndarray:
        return self._handle(self._weight_map[key]).get_tensor(key)


# Layer-container prefixes across families (bare, container-less
# exports drop the leading "model."/"transformer."): the SINGLE place
# the streamed loader's layer-key detection and the streamed saver's
# shard-key renaming agree on.
_LAYER_KEY_PAT = None


def _layer_key_pat():
    global _LAYER_KEY_PAT
    if _LAYER_KEY_PAT is None:
        import re
        _LAYER_KEY_PAT = re.compile(
            r"^((?:model\.layers|transformer\.h|layers|h)\.)0\.")
    return _LAYER_KEY_PAT


class PrefixedStateView:
    """Lazy key-rename view for bare (headless) HF exports whose keys
    lack a container prefix (e.g. GPT2Model without ``transformer.``):
    behaves like the renamed dict without materializing the state, so
    the streamed loader's one-tensor-at-a-time discipline survives."""

    def __init__(self, base, prefix: str,
                 passthrough: tuple = ("lm_head.weight",)):
        self._base = base
        self._prefix = prefix
        self._passthrough = passthrough

    def _map(self, key: str) -> str:
        if key in self._passthrough or not key.startswith(self._prefix):
            return key
        return key[len(self._prefix):]

    def __contains__(self, key: str) -> bool:
        return self._map(key) in self._base

    def __getitem__(self, key: str) -> np.ndarray:
        return self._base[self._map(key)]


class _LayerKeyView:
    """Remap a single-layer converter's layer-0 keys onto layer ``i``
    of the real checkpoint (``model.layers.0.`` -> ``model.layers.i.``,
    ``transformer.h.0.`` -> ``transformer.h.i.``). Keys the layer
    pattern does NOT match (embeddings, final norm, head) are memoized
    across views: the converter rebuilds the full single-layer pytree
    once per layer, and without the cache those multi-GB tensors would
    be re-read from disk n_layers times for nothing (only the i==0
    copies are kept)."""

    def __init__(self, base, layer: int, nonlayer_cache: dict):
        self._base = base
        self._sub = r"\g<1>%d." % layer
        self._cache = nonlayer_cache

    def _map(self, key: str) -> str:
        return _layer_key_pat().sub(self._sub, key)

    def __contains__(self, key: str) -> bool:
        return self._map(key) in self._base

    def __getitem__(self, key: str) -> np.ndarray:
        mapped = self._map(key)
        # memoize only TRUE non-layer keys (pattern match, not
        # mapped == key: for layer 0 the substitution is the identity
        # and the equality test would cache a whole extra layer of
        # weights for the lifetime of the load)
        if _layer_key_pat().match(key) is None:
            if key not in self._cache:
                self._cache[key] = self._base[key]
            return self._cache[key]
        return self._base[mapped]


def load_hf_checkpoint_streamed(path: str, mesh, family: Optional[str] = None,
                                is_critic: bool = False,
                                param_dtype: Optional[str] = None):
    """Host-RAM-bounded checkpoint load directly onto a device mesh.

    ``load_hf_checkpoint`` materializes the full model in host RAM
    before placement -- fine up to ~10B, impossible for the 70B the
    framework targets (140 GB bf16 against typical host RAM). This
    variant streams: the family converter runs once per transformer
    layer on a single-layer view of the checkpoint (safetensors
    ``safe_open`` reads one tensor at a time), each layer slice is cast
    and written into preallocated sharded device buffers with a
    donating ``dynamic_update_slice``, and only the non-stacked leaves
    (embeddings, final norm, head) are ever fully resident on host.
    Peak host memory = one transformer layer + embeddings. The
    reference's per-rank shard loading (``hf_registry.load:62``) solves
    the same problem GPU-side.

    Returns ``(cfg, params)`` with every leaf a global device array
    sharded per ``models/sharding.py`` rules on ``mesh`` (vocab already
    Megatron-padded for the mesh's tp) -- hand to ``Engine`` with
    ``already_sharded`` semantics (its device_put is then a no-op).
    """
    import copy

    import jax
    import jax.numpy as jnp

    from realhf_tpu.models import sharding as shard_rules
    from realhf_tpu.models import transformer as T

    family = family or detect_family(path)
    with open(os.path.join(path, "config.json")) as f:
        hf_config = json.load(f)
    cfg = config_from_hf(family, hf_config, is_critic=is_critic)
    if param_dtype is not None:
        cfg.param_dtype = param_dtype
    tdt = np.dtype(jnp.dtype(cfg.param_dtype).name)
    tp = int(mesh.shape.get("model", 1))

    state = _LazyShardState(path)
    cfg1 = copy.copy(cfg)
    cfg1.n_layers = 1

    shardings = shard_rules.param_shardings(cfg, mesh)

    def put_full(leaf, sh):
        return jax.device_put(np.asarray(leaf).astype(tdt, copy=False), sh)

    write_cache: Dict[Any, Any] = {}

    def write_slice(buf, sl, i, sh):
        key = (buf.shape, buf.dtype, sh)
        if key not in write_cache:
            write_cache[key] = jax.jit(
                lambda b, s, j: jax.lax.dynamic_update_slice_in_dim(
                    b, s, j, axis=0),
                donate_argnums=0, out_shardings=sh)
        return write_cache[key](buf, sl.astype(tdt, copy=False),
                                jnp.int32(i))

    def sharding_at(kp):
        """Leaf sharding looked up BY PATH (a critic's converter pytree
        has no "head" until the value head lands below, so positional
        zips against the shardings pytree would misalign)."""
        node = shardings
        for entry in kp:
            node = node[entry.key]
        return node

    params: Optional[Dict[str, Any]] = None
    p_flat_sh = []
    nonlayer_cache: Dict[str, np.ndarray] = {}
    for i in range(cfg.n_layers):
        sub = params_from_hf(family,
                             _LayerKeyView(state, i, nonlayer_cache),
                             cfg1)
        if i == 0:
            # Vocab-dim leaves pad to the tp multiple host-side (tiny:
            # embeddings only), matching Engine.normalize_vocab_padding.
            sub = shard_rules.normalize_vocab_padding(cfg1, sub, tp)
            sub_flat = jax.tree_util.tree_flatten_with_path(sub)[0]
            treedef = jax.tree_util.tree_structure(sub)
            leaves = []
            for kp, leaf in sub_flat:
                sh = sharding_at(kp)
                p_flat_sh.append(sh)
                if kp and getattr(kp[0], "key", None) == "blocks":
                    full_shape = (cfg.n_layers,) + tuple(leaf.shape[1:])
                    buf = jax.jit(
                        lambda shp=full_shape: jnp.zeros(shp, tdt),
                        out_shardings=sh)()
                    leaves.append(write_slice(buf, leaf, 0, sh))
                else:
                    leaves.append(put_full(leaf, sh))
            params = jax.tree_util.tree_unflatten(treedef, leaves)
        else:
            sub_flat = jax.tree_util.tree_flatten_with_path(sub)[0]
            p_leaves = jax.tree_util.tree_leaves(params)
            new_leaves = []
            for (kp, leaf), buf, sh in zip(sub_flat, p_leaves, p_flat_sh):
                if kp and getattr(kp[0], "key", None) == "blocks":
                    new_leaves.append(write_slice(buf, leaf, i, sh))
                else:
                    new_leaves.append(buf)  # embed/norm/head: done at i=0
            params = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(params), new_leaves)

    vh_path = os.path.join(path, _VALUE_HEAD_NAME)
    if is_critic:
        import safetensors.numpy
        if os.path.exists(vh_path):
            vh = safetensors.numpy.load_file(vh_path)
            w = vh["value_head.weight"]
        else:
            rng = np.random.RandomState(0)
            w = rng.normal(0, 0.02,
                           size=(cfg.hidden_dim, 1)).astype(np.float32)
            logger.info("Initialized critic value head from scratch.")
        params["head"] = {"w": put_full(w, shardings["head"]["w"])}
    return cfg, params


def save_hf_checkpoint(path: str, family: str, cfg: TransformerConfig,
                       params: Dict[str, Any],
                       tokenizer: Optional[Any] = None):
    """Write an HF-layout directory (config.json + sharded safetensors
    + index). The actor output loads directly into HF `from_pretrained`."""
    import safetensors.numpy

    os.makedirs(path, exist_ok=True)
    params = _to_numpy(params)

    value_head = None
    if cfg.is_critic:
        value_head = params.pop("head")["w"]

    state = params_to_hf(family, params, cfg)

    hf_cfg = config_to_hf(family, cfg)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)

    # Split into ~2GB shards with an index json.
    shards, current, current_bytes = [], {}, 0
    for k, v in state.items():
        if current and current_bytes + v.nbytes > _SHARD_SIZE:
            shards.append(current)
            current, current_bytes = {}, 0
        current[k] = v
        current_bytes += v.nbytes
    shards.append(current)

    if len(shards) == 1:
        safetensors.numpy.save_file(shards[0],
                                    os.path.join(path, "model.safetensors"))
    else:
        weight_map = {}
        for i, shard in enumerate(shards):
            name = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
            safetensors.numpy.save_file(shard, os.path.join(path, name))
            weight_map.update({k: name for k in shard})
        with open(os.path.join(path, _INDEX_NAME), "w") as f:
            json.dump({"metadata": {"total_size": sum(
                v.nbytes for s in shards for v in s.values())},
                "weight_map": weight_map}, f, indent=2)

    if value_head is not None:
        safetensors.numpy.save_file(
            {"value_head.weight": value_head},
            os.path.join(path, _VALUE_HEAD_NAME))
    if tokenizer is not None and hasattr(tokenizer, "save_pretrained"):
        tokenizer.save_pretrained(path)
    logger.info("Saved %s checkpoint to %s", family, path)


# Per-mesh cache of the collective gather/slice jits the streamed save
# uses: fresh lambdas would retrace + recompile one program per leaf
# shape on EVERY periodic checkpoint.
_STREAM_SAVE_JITS: Dict[Any, Any] = {}


def _stream_save_jits(mesh):
    if mesh not in _STREAM_SAVE_JITS:
        import jax

        rep = jax.sharding.NamedSharding(mesh,
                                         jax.sharding.PartitionSpec())
        _STREAM_SAVE_JITS[mesh] = (
            jax.jit(lambda x: x, out_shardings=rep),
            jax.jit(
                lambda b, j: jax.lax.dynamic_slice_in_dim(b, j, 1,
                                                          axis=0),
                out_shardings=rep))
    return _STREAM_SAVE_JITS[mesh]


def save_hf_checkpoint_streamed(path: str, family: str,
                                cfg: TransformerConfig,
                                params: Dict[str, Any],
                                tokenizer: Optional[Any] = None,
                                writer: bool = True):
    """Host-RAM-bounded HF save: one safetensors shard per transformer
    layer, written from a single-layer slice of the (device-resident,
    possibly sharded) params -- the mirror of
    ``load_hf_checkpoint_streamed``. Peak host memory is one layer
    plus the non-stacked leaves (embeddings, norms, head), where the
    eager ``save_hf_checkpoint`` holds the full model TWICE (numpy
    pytree + converted HF state dict).

    On a PROCESS-SPANNING mesh this is a COLLECTIVE: every member of
    the mesh must call it together (each per-layer slice is gathered
    by a replicating jit all members join -- the per-layer schedule of
    the reference's per-rank shard IO, ``conversion/hf_registry.py``);
    only the process with ``writer=True`` touches the filesystem.
    """
    import copy

    import jax
    import safetensors.numpy

    procs = {d.process_index
             for leaf in jax.tree.leaves(params)
             if hasattr(leaf, "sharding")
             for d in leaf.sharding.device_set}
    multiproc = len(procs) > 1
    if multiproc:
        mesh = next(leaf.sharding.mesh for leaf in jax.tree.leaves(params)
                    if hasattr(leaf, "sharding"))
        gather_jit, slice_jit = _stream_save_jits(mesh)

    def to_host(leaf):
        """One leaf to host; replicating collective gather on a
        process-spanning mesh (every member holds a full copy after,
        so np.asarray reads process-local data)."""
        return np.asarray(gather_jit(leaf) if multiproc else leaf)

    def layer_slice(leaf, i):
        """Stacked-leaf layer i as a [1, ...] host array."""
        if multiproc:
            return np.asarray(slice_jit(leaf, i))
        return np.asarray(leaf[i:i + 1])

    # Writer-side IO errors are RECORDED, not raised, until every
    # collective gather below has run: aborting early would leave the
    # other mesh members blocked in a gather the writer never joins.
    io_error: Optional[BaseException] = None
    if writer:
        try:
            os.makedirs(path, exist_ok=True)
        except OSError as e:
            io_error = e
    cfg1 = copy.copy(cfg)
    cfg1.n_layers = 1
    pat = _layer_key_pat()

    params = dict(params)
    value_head = None
    if cfg.is_critic:
        value_head = to_host(params.pop("head")["w"])

    # Non-stacked leaves: one host gather, vocab-unpadded, reused by
    # every per-layer conversion pass (the converters emit them each
    # pass; only pass 0's copies are written).
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    nonlayer_host = {}
    from realhf_tpu.models.sharding import repad_vocab_leaf
    for kp, leaf in flat:
        if not (kp and getattr(kp[0], "key", None) == "blocks"):
            keypath = tuple(e.key for e in kp)
            # checkpoints store the true vocab; the device copy is
            # Megatron-padded for its tp (repad to tp=1 == unpad)
            nonlayer_host[keypath] = repad_vocab_leaf(
                cfg, keypath, to_host(leaf), target_tp=1)

    if writer and io_error is None:
        try:
            with open(os.path.join(path, "config.json"), "w") as f:
                json.dump(config_to_hf(family, cfg), f, indent=2)
        except OSError as e:
            io_error = e

    n_files = cfg.n_layers + 1
    weight_map: Dict[str, str] = {}
    total_bytes = 0

    def write_file(idx: int, state: StateDict):
        nonlocal total_bytes, io_error
        if not writer or io_error is not None:
            return
        # A writer-side IO failure (ENOSPC, quota) must NOT abort the
        # per-layer loop: on a process-spanning mesh the members keep
        # running the collective gathers and would deadlock waiting
        # for the writer to join. Record the error, keep pace with
        # the collective schedule, re-raise once the loop completes.
        try:
            name = f"model-{idx + 1:05d}-of-{n_files:05d}.safetensors"
            safetensors.numpy.save_file(state, os.path.join(path, name))
            weight_map.update({k: name for k in state})
            total_bytes += sum(v.nbytes for v in state.values())
        except Exception as e:  # noqa: BLE001 - SafetensorError is not
            # an OSError; any writer-side failure must keep the loop
            # (and with it the collective schedule) running
            io_error = e

    # i>0 passes only keep the LAYER keys of the converter output, so
    # the non-layer leaves get rank-preserving 1-element stand-ins
    # there -- converting real multi-GB embeddings n_layers times
    # would dominate the save this function exists to make cheap.
    nonlayer_dummy = {
        k: np.zeros((1,) * v.ndim, v.dtype)
        for k, v in nonlayer_host.items()}

    for i in range(cfg.n_layers):
        leaves = []
        for kp, leaf in flat:
            if kp and getattr(kp[0], "key", None) == "blocks":
                leaves.append(layer_slice(leaf, i))
            else:
                keypath = tuple(e.key for e in kp)
                leaves.append(nonlayer_host[keypath] if i == 0
                              else nonlayer_dummy[keypath])
        tree_i = jax.tree_util.tree_unflatten(treedef, leaves)
        state_i = params_to_hf(family, tree_i, cfg1)
        layer_state = {
            pat.sub(r"\g<1>%d." % i, k): v
            for k, v in state_i.items() if pat.match(k)}
        write_file(i, layer_state)
        if i == 0:
            write_file(cfg.n_layers, {k: v for k, v in state_i.items()
                                      if not pat.match(k)})

    if not writer:
        return
    if io_error is not None:
        raise io_error
    with open(os.path.join(path, _INDEX_NAME), "w") as f:
        json.dump({"metadata": {"total_size": total_bytes},
                   "weight_map": weight_map}, f, indent=2)

    if value_head is not None:
        safetensors.numpy.save_file(
            {"value_head.weight": value_head},
            os.path.join(path, _VALUE_HEAD_NAME))
    if tokenizer is not None and hasattr(tokenizer, "save_pretrained"):
        tokenizer.save_pretrained(path)
    logger.info("Saved %s checkpoint (streamed, %d shards) to %s",
                family, n_files, path)


def _to_numpy(tree):
    import jax
    return jax.tree.map(lambda x: np.asarray(x), tree)


# ----------------------------------------------------------------------
# Helpers shared by family converters
# ----------------------------------------------------------------------
def stack_layers(state: StateDict, pattern: str, n_layers: int,
                 transpose: bool = False) -> np.ndarray:
    """Collect per-layer HF keys `pattern.format(i)` into one stacked
    array [n_layers, ...]; HF Linear weights are (out, in) so
    ``transpose=True`` yields the framework's (in, out)."""
    mats = []
    for i in range(n_layers):
        w = state[pattern.format(i)]
        mats.append(w.T if transpose else w)
    return np.stack(mats, axis=0)


def unstack_layers(arr: np.ndarray, pattern: str, out: StateDict,
                   transpose: bool = False):
    for i in range(arr.shape[0]):
        w = arr[i]
        out[pattern.format(i)] = np.ascontiguousarray(w.T if transpose else w)
