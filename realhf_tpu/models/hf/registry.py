"""Registry of HuggingFace model-family converters.

Parity with reference ``realhf/impl/model/conversion/hf_registry.py``
(HFModelRegistry:25): each family supplies config and weight mappings
in both directions; checkpoints are HF-compatible safetensors with an
index json, so actors trained here load directly into HF/vLLM
(reference ``docs/source/arch.rst:118-127``). Critic value heads are
stored as an extra ``value_head.safetensors`` alongside the HF layout
(the reference likewise uses a ReaL-only critic format).

Weights convert between the framework's stacked-layer pytree
(layer-stacked arrays, transformer.py) and HF's per-layer (out, in)
torch convention.
"""

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Optional

import numpy as np

from realhf_tpu.base import logging
from realhf_tpu.models.config import TransformerConfig

logger = logging.getLogger("hf_registry")

StateDict = Dict[str, np.ndarray]


@dataclasses.dataclass
class HFFamily:
    name: str
    hf_model_type: str
    # TransformerConfig <-> HF config dict (kwargs of the HF config class)
    config_from_hf: Callable[[Dict[str, Any], bool], TransformerConfig]
    config_to_hf: Callable[[TransformerConfig], Dict[str, Any]]
    # stacked pytree <-> HF flat state dict of numpy arrays
    params_from_hf: Callable[[StateDict, TransformerConfig], Dict[str, Any]]
    params_to_hf: Callable[[Dict[str, Any], TransformerConfig], StateDict]


HF_FAMILIES: Dict[str, HFFamily] = {}


def register_hf_family(family: HFFamily):
    if family.name in HF_FAMILIES:
        raise ValueError(f"HF family {family.name} already registered.")
    HF_FAMILIES[family.name] = family


def config_from_hf(family: str, hf_config: Any,
                   is_critic: bool = False) -> TransformerConfig:
    d = hf_config if isinstance(hf_config, dict) else hf_config.to_dict()
    return HF_FAMILIES[family].config_from_hf(d, is_critic)


def config_to_hf(family: str, cfg: TransformerConfig) -> Dict[str, Any]:
    return HF_FAMILIES[family].config_to_hf(cfg)


def params_from_hf(family: str, state_dict: StateDict,
                   cfg: TransformerConfig) -> Dict[str, Any]:
    return HF_FAMILIES[family].params_from_hf(state_dict, cfg)


def params_to_hf(family: str, params: Dict[str, Any],
                 cfg: TransformerConfig) -> StateDict:
    return HF_FAMILIES[family].params_to_hf(params, cfg)


# ----------------------------------------------------------------------
# Checkpoint IO (sharded safetensors + index, reference hf_registry
# save:201 / load:62 + base/saveload_utils.py:14)
# ----------------------------------------------------------------------
_INDEX_NAME = "model.safetensors.index.json"
_VALUE_HEAD_NAME = "value_head.safetensors"
_SHARD_SIZE = 2 * 1024 ** 3  # bytes per safetensors shard


def detect_family(path: str) -> str:
    with open(os.path.join(path, "config.json")) as f:
        mt = json.load(f)["model_type"]
    for fam in HF_FAMILIES.values():
        if fam.hf_model_type == mt:
            return fam.name
    raise ValueError(f"No registered family for HF model_type={mt}")


def load_hf_checkpoint(path: str, family: Optional[str] = None,
                       is_critic: bool = False):
    """Read an HF-layout directory -> (TransformerConfig, params pytree).

    All shards are materialized in host RAM, then device_put with the
    target sharding does the placement. (The reference instead reads
    only the shards each rank needs, hf_registry.load:62; a streaming
    per-host loader is a planned optimization for >host-RAM models.)
    """
    import safetensors.numpy

    family = family or detect_family(path)
    with open(os.path.join(path, "config.json")) as f:
        hf_config = json.load(f)
    cfg = config_from_hf(family, hf_config, is_critic=is_critic)

    state: StateDict = {}
    index_path = os.path.join(path, _INDEX_NAME)
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        shards = sorted(set(index["weight_map"].values()))
        for shard in shards:
            state.update(safetensors.numpy.load_file(os.path.join(path, shard)))
    else:
        state.update(safetensors.numpy.load_file(
            os.path.join(path, "model.safetensors")))
    params = params_from_hf(family, state, cfg)

    vh_path = os.path.join(path, _VALUE_HEAD_NAME)
    if is_critic:
        if os.path.exists(vh_path):
            vh = safetensors.numpy.load_file(vh_path)
            params["head"] = {"w": vh["value_head.weight"]}
        else:
            # init_critic_from_actor: drop the LM head, fresh value head
            # (reference model_api.py / hf_registry load path).
            rng = np.random.RandomState(0)
            params["head"] = {"w": rng.normal(
                0, 0.02, size=(cfg.hidden_dim, 1)).astype(np.float32)}
            logger.info("Initialized critic value head from scratch.")
    return cfg, params


def save_hf_checkpoint(path: str, family: str, cfg: TransformerConfig,
                       params: Dict[str, Any],
                       tokenizer: Optional[Any] = None):
    """Write an HF-layout directory (config.json + sharded safetensors
    + index). The actor output loads directly into HF `from_pretrained`."""
    import safetensors.numpy

    os.makedirs(path, exist_ok=True)
    params = _to_numpy(params)

    value_head = None
    if cfg.is_critic:
        value_head = params.pop("head")["w"]

    state = params_to_hf(family, params, cfg)

    hf_cfg = config_to_hf(family, cfg)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)

    # Split into ~2GB shards with an index json.
    shards, current, current_bytes = [], {}, 0
    for k, v in state.items():
        if current and current_bytes + v.nbytes > _SHARD_SIZE:
            shards.append(current)
            current, current_bytes = {}, 0
        current[k] = v
        current_bytes += v.nbytes
    shards.append(current)

    if len(shards) == 1:
        safetensors.numpy.save_file(shards[0],
                                    os.path.join(path, "model.safetensors"))
    else:
        weight_map = {}
        for i, shard in enumerate(shards):
            name = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
            safetensors.numpy.save_file(shard, os.path.join(path, name))
            weight_map.update({k: name for k in shard})
        with open(os.path.join(path, _INDEX_NAME), "w") as f:
            json.dump({"metadata": {"total_size": sum(
                v.nbytes for s in shards for v in s.values())},
                "weight_map": weight_map}, f, indent=2)

    if value_head is not None:
        safetensors.numpy.save_file(
            {"value_head.weight": value_head},
            os.path.join(path, _VALUE_HEAD_NAME))
    if tokenizer is not None and hasattr(tokenizer, "save_pretrained"):
        tokenizer.save_pretrained(path)
    logger.info("Saved %s checkpoint to %s", family, path)


def _to_numpy(tree):
    import jax
    return jax.tree.map(lambda x: np.asarray(x), tree)


# ----------------------------------------------------------------------
# Helpers shared by family converters
# ----------------------------------------------------------------------
def stack_layers(state: StateDict, pattern: str, n_layers: int,
                 transpose: bool = False) -> np.ndarray:
    """Collect per-layer HF keys `pattern.format(i)` into one stacked
    array [n_layers, ...]; HF Linear weights are (out, in) so
    ``transpose=True`` yields the framework's (in, out)."""
    mats = []
    for i in range(n_layers):
        w = state[pattern.format(i)]
        mats.append(w.T if transpose else w)
    return np.stack(mats, axis=0)


def unstack_layers(arr: np.ndarray, pattern: str, out: StateDict,
                   transpose: bool = False):
    for i in range(arr.shape[0]):
        w = arr[i]
        out[pattern.format(i)] = np.ascontiguousarray(w.T if transpose else w)
