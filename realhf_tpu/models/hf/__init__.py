"""HuggingFace checkpoint conversion; importing registers families.

Parity with reference ``realhf/api/from_hf/__init__.py`` +
``impl/model/conversion/hf_registry.py``.
"""

import realhf_tpu.models.hf.llama  # noqa: F401
import realhf_tpu.models.hf.gpt2  # noqa: F401
import realhf_tpu.models.hf.mixtral  # noqa: F401
import realhf_tpu.models.hf.gemma  # noqa: F401

from realhf_tpu.models.hf.registry import (  # noqa: F401
    HF_FAMILIES,
    config_from_hf,
    config_to_hf,
    load_hf_checkpoint,
    load_hf_checkpoint_streamed,
    params_from_hf,
    params_to_hf,
    register_hf_family,
    save_hf_checkpoint,
    save_hf_checkpoint_streamed,
)
