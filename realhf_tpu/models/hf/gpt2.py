"""GPT-2 HF conversion.

Parity with reference ``realhf/api/from_hf/gpt2.py``. GPT-2 uses
absolute positions, fused QKV stored as Conv1D (weights already in
(in, out) orientation -- no transpose), LayerNorm with bias, gelu_new,
and tied embeddings.
"""

from typing import Any, Dict

import numpy as np

from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.models.hf.registry import (
    HFFamily,
    StateDict,
    register_hf_family,
    stack_layers,
    unstack_layers,
)


def _config_from_hf(d: Dict[str, Any], is_critic: bool) -> TransformerConfig:
    return TransformerConfig(
        n_layers=d["n_layer"],
        n_kv_heads=d["n_head"],
        n_q_heads=d["n_head"],
        hidden_dim=d["n_embd"],
        intermediate_dim=d.get("n_inner") or 4 * d["n_embd"],
        vocab_size=d["vocab_size"],
        n_positions=d["n_positions"],
        layer_norm_epsilon=d.get("layer_norm_epsilon", 1e-5),
        activation_function={"gelu_new": "gelu_new", "gelu": "gelu",
                             "gelu_pytorch_tanh": "gelu_new"}[
            d.get("activation_function", "gelu_new")],
        scale_attn_by_inverse_layer_idx=d.get(
            "scale_attn_by_inverse_layer_idx", False),
        use_attention_bias=True,
        use_attn_proj_bias=True,
        use_mlp_bias=True,
        layer_norm_type=None,
        mlp_type=None,
        apply_rotary=False,
        tied_embedding=True,
        is_critic=is_critic,
        embd_pdrop=d.get("embd_pdrop", 0.0),
        resid_pdrop=d.get("resid_pdrop", 0.0),
        attn_pdrop=d.get("attn_pdrop", 0.0),
    )


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    return {
        "model_type": "gpt2",
        "architectures": ["GPT2LMHeadModel"],
        "n_layer": cfg.n_layers,
        "n_head": cfg.n_q_heads,
        "n_embd": cfg.hidden_dim,
        "n_inner": cfg.intermediate_dim,
        "n_positions": cfg.n_positions,
        "n_ctx": cfg.n_positions,
        "vocab_size": cfg.vocab_size,
        "layer_norm_epsilon": cfg.layer_norm_epsilon,
        "activation_function": cfg.activation_function,
        "scale_attn_by_inverse_layer_idx": cfg.scale_attn_by_inverse_layer_idx,
        "embd_pdrop": cfg.embd_pdrop,
        "resid_pdrop": cfg.resid_pdrop,
        "attn_pdrop": cfg.attn_pdrop,
        "tie_word_embeddings": True,
        "torch_dtype": "float32",
    }


def _params_from_hf(state: StateDict, cfg: TransformerConfig) -> Dict[str, Any]:
    nl, h = cfg.n_layers, cfg.hidden_dim
    pre = "transformer.h.{}."
    if "transformer.wte.weight" not in state:  # bare GPT2Model naming
        from realhf_tpu.models.hf.registry import PrefixedStateView
        state = PrefixedStateView(state, "transformer.")
    # Fused QKV (Conv1D, (in, 3h)) -> separate (in, out) mats.
    c_attn_w = stack_layers(state, pre + "attn.c_attn.weight", nl)  # [nl, h, 3h]
    c_attn_b = stack_layers(state, pre + "attn.c_attn.bias", nl)    # [nl, 3h]
    wq, wk, wv = np.split(c_attn_w, 3, axis=2)
    bq, bk, bv = np.split(c_attn_b, 3, axis=1)
    params: Dict[str, Any] = {
        "embed": {
            "wte": state["transformer.wte.weight"],
            "wpe": state["transformer.wpe.weight"],
        },
        "blocks": {
            "ln1": {
                "scale": stack_layers(state, pre + "ln_1.weight", nl),
                "bias": stack_layers(state, pre + "ln_1.bias", nl),
            },
            "attn": {
                "wq": wq, "wk": wk, "wv": wv,
                "bq": bq, "bk": bk, "bv": bv,
                "wo": stack_layers(state, pre + "attn.c_proj.weight", nl),
                "bo": stack_layers(state, pre + "attn.c_proj.bias", nl),
            },
            "ln2": {
                "scale": stack_layers(state, pre + "ln_2.weight", nl),
                "bias": stack_layers(state, pre + "ln_2.bias", nl),
            },
            "mlp": {
                "wu": stack_layers(state, pre + "mlp.c_fc.weight", nl),
                "bu": stack_layers(state, pre + "mlp.c_fc.bias", nl),
                "wd": stack_layers(state, pre + "mlp.c_proj.weight", nl),
                "bd": stack_layers(state, pre + "mlp.c_proj.bias", nl),
            },
        },
        "ln_f": {
            "scale": state["transformer.ln_f.weight"],
            "bias": state["transformer.ln_f.bias"],
        },
    }
    return params


def _params_to_hf(params: Dict[str, Any], cfg: TransformerConfig) -> StateDict:
    out: StateDict = {}
    pre = "transformer.h.{}."
    out["transformer.wte.weight"] = np.ascontiguousarray(params["embed"]["wte"])
    out["transformer.wpe.weight"] = np.ascontiguousarray(params["embed"]["wpe"])
    b = params["blocks"]
    unstack_layers(b["ln1"]["scale"], pre + "ln_1.weight", out)
    unstack_layers(b["ln1"]["bias"], pre + "ln_1.bias", out)
    c_attn_w = np.concatenate(
        [b["attn"]["wq"], b["attn"]["wk"], b["attn"]["wv"]], axis=2)
    c_attn_b = np.concatenate(
        [b["attn"]["bq"], b["attn"]["bk"], b["attn"]["bv"]], axis=1)
    unstack_layers(c_attn_w, pre + "attn.c_attn.weight", out)
    unstack_layers(c_attn_b, pre + "attn.c_attn.bias", out)
    unstack_layers(b["attn"]["wo"], pre + "attn.c_proj.weight", out)
    unstack_layers(b["attn"]["bo"], pre + "attn.c_proj.bias", out)
    unstack_layers(b["ln2"]["scale"], pre + "ln_2.weight", out)
    unstack_layers(b["ln2"]["bias"], pre + "ln_2.bias", out)
    unstack_layers(b["mlp"]["wu"], pre + "mlp.c_fc.weight", out)
    unstack_layers(b["mlp"]["bu"], pre + "mlp.c_fc.bias", out)
    unstack_layers(b["mlp"]["wd"], pre + "mlp.c_proj.weight", out)
    unstack_layers(b["mlp"]["bd"], pre + "mlp.c_proj.bias", out)
    out["transformer.ln_f.weight"] = np.ascontiguousarray(
        params["ln_f"]["scale"])
    out["transformer.ln_f.bias"] = np.ascontiguousarray(params["ln_f"]["bias"])
    out["lm_head.weight"] = out["transformer.wte.weight"]
    return out


register_hf_family(HFFamily(
    name="gpt2", hf_model_type="gpt2",
    config_from_hf=_config_from_hf,
    config_to_hf=_config_to_hf,
    params_from_hf=_params_from_hf,
    params_to_hf=_params_to_hf,
))
