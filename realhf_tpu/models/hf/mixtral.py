"""Mixtral (MoE llama) HF conversion.

Parity with reference ``realhf/api/from_hf/mixtral.py``: llama
attention + block-sparse MoE FFN. HF per-expert w1 (gate), w3 (up),
w2 (down) stack into [E, H, F] / [E, F, H]; the router gate becomes
[H, E].
"""

from typing import Any, Dict

import numpy as np

from realhf_tpu.models.config import MoEConfig, TransformerConfig
from realhf_tpu.models.hf.llama import (
    _config_to_hf_llama,
    llama_backbone_from_hf,
    llama_backbone_to_hf,
)
from realhf_tpu.models.hf.registry import (
    HFFamily,
    StateDict,
    register_hf_family,
    stack_layers,
    unstack_layers,
)


def _config_from_hf(d: Dict[str, Any], is_critic: bool) -> TransformerConfig:
    nq = d["num_attention_heads"]
    return TransformerConfig(
        n_layers=d["num_hidden_layers"],
        n_kv_heads=d.get("num_key_value_heads", nq),
        n_q_heads=nq,
        hidden_dim=d["hidden_size"],
        head_dim=d.get("head_dim") or d["hidden_size"] // nq,
        intermediate_dim=d["intermediate_size"],
        vocab_size=d["vocab_size"],
        n_positions=d.get("max_position_embeddings"),
        layer_norm_epsilon=d.get("rms_norm_eps", 1e-5),
        activation_function="silu",
        use_attention_bias=False,
        use_attn_proj_bias=False,
        use_mlp_bias=False,
        layer_norm_type="rms",
        mlp_type="moe",
        apply_rotary=True,
        rotary_base=d.get("rope_theta", 1e6),
        scale_attn_by_inverse_layer_idx=False,
        tied_embedding=d.get("tie_word_embeddings", False),
        sliding_window=d.get("sliding_window"),
        moe=MoEConfig(
            num_experts=d.get("num_local_experts", 8),
            top_k=d.get("num_experts_per_tok", 2),
            routing_type="aux_loss",
            aux_loss_coeff=d.get("router_aux_loss_coef", 1e-2)),
        is_critic=is_critic,
    )


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    d = _config_to_hf_llama(cfg, "llama")
    d.update({
        "model_type": "mixtral",
        "architectures": ["MixtralForCausalLM"],
        "num_local_experts": cfg.moe.num_experts,
        "num_experts_per_tok": cfg.moe.top_k,
        "router_aux_loss_coef": cfg.moe.aux_loss_coeff,
    })
    d.pop("attention_bias", None)
    return d


def _params_from_hf(state: StateDict, cfg: TransformerConfig) -> Dict[str, Any]:
    nl = cfg.n_layers
    ne = cfg.moe.num_experts
    pre = "model.layers.{}."
    # Attention/norm/embedding/head layout equals llama.
    params = llama_backbone_from_hf(state, cfg)
    mlp = params["blocks"]["mlp"]
    mlp["router"] = stack_layers(
        state, pre + "block_sparse_moe.gate.weight", nl, transpose=True)
    for name, hf_w, transpose in (("wg", "w1", True), ("wu", "w3", True),
                                  ("wd", "w2", True)):
        per_layer = []
        for i in range(nl):
            per_expert = [
                state[f"model.layers.{i}.block_sparse_moe.experts."
                      f"{e}.{hf_w}.weight"].T
                for e in range(ne)
            ]
            per_layer.append(np.stack(per_expert, axis=0))
        mlp[name] = np.stack(per_layer, axis=0)  # [nl, E, in, out]
    return params


def _params_to_hf(params: Dict[str, Any], cfg: TransformerConfig) -> StateDict:
    out: StateDict = {}
    pre = "model.layers.{}."
    llama_backbone_to_hf(params, cfg, out)
    b = params["blocks"]
    unstack_layers(b["mlp"]["router"], pre + "block_sparse_moe.gate.weight",
                   out, transpose=True)
    nl, ne = cfg.n_layers, cfg.moe.num_experts
    for name, hf_w in (("wg", "w1"), ("wu", "w3"), ("wd", "w2")):
        arr = b["mlp"][name]  # [nl, E, in, out]
        for i in range(nl):
            for e in range(ne):
                out[f"model.layers.{i}.block_sparse_moe.experts."
                    f"{e}.{hf_w}.weight"] = np.ascontiguousarray(arr[i, e].T)
    return out


register_hf_family(HFFamily(
    name="mixtral", hf_model_type="mixtral",
    config_from_hf=_config_from_hf,
    config_to_hf=_config_to_hf,
    params_from_hf=_params_from_hf,
    params_to_hf=_params_to_hf,
))
