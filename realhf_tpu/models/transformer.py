"""The single transformer implementation used for every model role.

TPU-native counterpart of reference ``realhf/impl/model/nn/
real_llm_api.py`` (ReaLModel) + ``real_llm_base.py`` + ``modules/``:
one decoder-only transformer covering actor / critic / reference /
reward roles (critic mode swaps the LM head for a scalar value head).

Design (idiomatic JAX, not a torch translation):
- Parameters are a plain dict pytree with **stacked** block weights
  (leading axis = layer). The whole stack is scanned with
  ``jax.lax.scan``, which keeps compile time O(1) in depth and makes
  resharding between meshes a single device_put of the pytree.
- Batches are packed streams ``[B, L]`` with segment ids (0 = pad);
  positions are derived per segment. DP shards B; TP shards heads and
  MLP; Megatron-style sequence parallelism falls out of GSPMD sharding
  constraints (see models/sharding.py).
- Generation uses a per-layer KV cache pytree and a single-token
  decode step; the jitted decode loop replaces CUDA-graph capture
  (reference ``nn/real_llm_generate.py:214``).

Layer indexing convention matches the reference (real_llm_base.py:394):
0 = embedding, 1..n_layers = blocks, n_layers+1 = head -- used by HF
conversion and (later) pipeline splitting.
"""

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from realhf_tpu.base.backend import pallas_enabled
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.ops.attention import decode_attention, packed_attention
from realhf_tpu.ops.rotary import apply_rotary, rotary_freqs

Params = Dict[str, Any]
KVCache = Dict[str, jnp.ndarray]


# ----------------------------------------------------------------------
# Initialization
# ----------------------------------------------------------------------
def init_params(cfg: TransformerConfig, key: jax.Array) -> Params:
    """Random-normal init (std 0.02, projection layers scaled by
    1/sqrt(2*n_layers) as in GPT-2/llama lineage)."""
    pdt = jnp.dtype(cfg.param_dtype)
    h, f, v = cfg.hidden_dim, cfg.intermediate_dim, cfg.vocab_size
    nl, hd = cfg.n_layers, cfg.head_dim
    nq, nkv = cfg.n_q_heads, cfg.n_kv_heads
    std = 0.02
    proj_std = std / (2 * nl) ** 0.5

    keys = jax.random.split(key, 16)

    def norm(shape, k, s=std):
        return (s * jax.random.normal(k, shape)).astype(pdt)

    def zeros(shape):
        return jnp.zeros(shape, dtype=pdt)

    def ones(shape):
        return jnp.ones(shape, dtype=pdt)

    params: Params = {
        "embed": {"wte": norm((v, h), keys[0])},
        "blocks": {
            "ln1": {"scale": ones((nl, h))},
            "attn": {
                "wq": norm((nl, h, nq * hd), keys[1]),
                "wk": norm((nl, h, nkv * hd), keys[2]),
                "wv": norm((nl, h, nkv * hd), keys[3]),
                "wo": norm((nl, nq * hd, h), keys[4], proj_std),
            },
            "ln2": {"scale": ones((nl, h))},
            "mlp": {},
        },
        "ln_f": {"scale": ones((h,))},
    }
    if cfg.uses_absolute_position:
        assert cfg.n_positions is not None
        params["embed"]["wpe"] = norm(
            (cfg.n_positions + cfg.abs_position_embedding_offset, h), keys[5])

    mlp = params["blocks"]["mlp"]
    if cfg.mlp_type == "moe":
        ne = cfg.moe.num_experts
        mlp["router"] = norm((nl, h, ne), keys[6])
        mlp["wg"] = norm((nl, ne, h, f), keys[7])
        mlp["wu"] = norm((nl, ne, h, f), keys[8])
        mlp["wd"] = norm((nl, ne, f, h), keys[9], proj_std)
    elif cfg.gated_mlp:
        mlp["wg"] = norm((nl, h, f), keys[7])
        mlp["wu"] = norm((nl, h, f), keys[8])
        mlp["wd"] = norm((nl, f, h), keys[9], proj_std)
    else:
        mlp["wu"] = norm((nl, h, f), keys[8])
        mlp["wd"] = norm((nl, f, h), keys[9], proj_std)

    if cfg.use_attention_bias:
        a = params["blocks"]["attn"]
        a["bq"], a["bk"], a["bv"] = (zeros((nl, nq * hd)),
                                     zeros((nl, nkv * hd)),
                                     zeros((nl, nkv * hd)))
    if cfg.use_attn_proj_bias:
        params["blocks"]["attn"]["bo"] = zeros((nl, h))
    if cfg.use_mlp_bias and cfg.mlp_type is None:
        mlp["bu"] = zeros((nl, f))
        mlp["bd"] = zeros((nl, h))
    if cfg.layer_norm_type is None:  # LayerNorm has bias; RMSNorm none
        params["blocks"]["ln1"]["bias"] = zeros((nl, h))
        params["blocks"]["ln2"]["bias"] = zeros((nl, h))
        params["ln_f"]["bias"] = zeros((h,))

    if cfg.is_critic:
        params["head"] = {"w": norm((h, 1), keys[10])}
    elif not cfg.tied_embedding:
        params["head"] = {"w": norm((h, v), keys[10])}
    return params


# ----------------------------------------------------------------------
# Building blocks
# ----------------------------------------------------------------------
def _norm(cfg: TransformerConfig, x: jnp.ndarray, scale: jnp.ndarray,
          bias: Optional[jnp.ndarray]) -> jnp.ndarray:
    """LayerNorm / RMSNorm / gemma-RMSNorm with fp32 accumulation."""
    xf = x.astype(jnp.float32)
    if cfg.layer_norm_type is None:
        mean = xf.mean(-1, keepdims=True)
        var = jnp.mean((xf - mean) ** 2, -1, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + cfg.layer_norm_epsilon)
        out = out * scale.astype(jnp.float32)
        if bias is not None:
            out = out + bias.astype(jnp.float32)
    elif cfg.layer_norm_type == "rms":
        var = jnp.mean(xf ** 2, -1, keepdims=True)
        out = xf * jax.lax.rsqrt(var + cfg.layer_norm_epsilon)
        out = out * scale.astype(jnp.float32)
    elif cfg.layer_norm_type == "gemma":
        var = jnp.mean(xf ** 2, -1, keepdims=True)
        out = xf * jax.lax.rsqrt(var + cfg.layer_norm_epsilon)
        out = out * (1.0 + scale.astype(jnp.float32))
    else:
        raise NotImplementedError(cfg.layer_norm_type)
    return out.astype(x.dtype)


def _activation(cfg: TransformerConfig, x: jnp.ndarray) -> jnp.ndarray:
    if cfg.activation_function == "silu":
        return jax.nn.silu(x)
    if cfg.activation_function == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if cfg.activation_function == "gelu_new":
        return jax.nn.gelu(x, approximate=True)
    raise NotImplementedError(cfg.activation_function)


def _mlp(cfg: TransformerConfig, lp: Params, x: jnp.ndarray,
         moe_constraint=None) -> jnp.ndarray:
    out, _ = _mlp_with_aux(cfg, lp, x, None, moe_constraint)
    return out


def _mlp_with_aux(cfg: TransformerConfig, lp: Params, x: jnp.ndarray,
                  seg_ids: Optional[jnp.ndarray] = None,
                  moe_constraint=None):
    """MLP returning (output, aux-loss dict) -- non-empty only for MoE
    (router load-balancing / z losses, reference utils/moe.py:395).
    ``seg_ids`` masks padding out of MoE routing/capacity/losses."""
    cdt = jnp.dtype(cfg.compute_dtype)
    m = lp["mlp"]
    if cfg.mlp_type == "moe":
        from realhf_tpu.ops.moe import moe_mlp_with_losses
        squeeze = x.ndim == 2  # decode step: [B, H]
        x3 = x[:, None, :] if squeeze else x
        valid = None if seg_ids is None else (seg_ids != 0)
        out, aux = moe_mlp_with_losses(cfg, m, x3, valid_mask=valid,
                                       ep_constraint=moe_constraint)
        return (out[:, 0] if squeeze else out), aux
    return _dense_mlp(cfg, m, x, cdt), {}


def _dense_mlp(cfg, m, x, cdt):
    if cfg.gated_mlp:
        gate = x @ m["wg"].astype(cdt)
        up = x @ m["wu"].astype(cdt)
        return _activation(cfg, gate) * up @ m["wd"].astype(cdt)
    up = x @ m["wu"].astype(cdt)
    if "bu" in m:
        up = up + m["bu"].astype(cdt)
    out = _activation(cfg, up) @ m["wd"].astype(cdt)
    if "bd" in m:
        out = out + m["bd"].astype(cdt)
    return out


def _qkv(cfg: TransformerConfig, lp: Params, x: jnp.ndarray):
    cdt = jnp.dtype(cfg.compute_dtype)
    a = lp["attn"]
    *lead, _ = x.shape
    q = x @ a["wq"].astype(cdt)
    k = x @ a["wk"].astype(cdt)
    v = x @ a["wv"].astype(cdt)
    if "bq" in a:
        q = q + a["bq"].astype(cdt)
        k = k + a["bk"].astype(cdt)
        v = v + a["bv"].astype(cdt)
    q = q.reshape(*lead, cfg.n_q_heads, cfg.head_dim)
    k = k.reshape(*lead, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(*lead, cfg.n_kv_heads, cfg.head_dim)
    return q, k, v


def _attn_scale(cfg: TransformerConfig, layer_idx: jnp.ndarray) -> jnp.ndarray:
    scale = cfg.head_dim ** -0.5 if cfg.scale_attn_weights else 1.0
    if cfg.scale_attn_by_inverse_layer_idx:
        scale = scale / (layer_idx.astype(jnp.float32) + 1.0)
    return scale


def _block(cfg: TransformerConfig, lp: Params, layer_idx: jnp.ndarray,
           x: jnp.ndarray, seg_ids: jnp.ndarray, cos: jnp.ndarray,
           sin: jnp.ndarray, constrain, attention_fn=None,
           moe_constraint=None):
    """One transformer block over packed streams [B, L, H]; returns
    (residual output, (k, v), aux-losses) -- k/v feed prefill KV
    caches; aux is non-empty for MoE."""
    ln1 = _norm(cfg, x, lp["ln1"]["scale"], lp["ln1"].get("bias"))
    q, k, v = _qkv(cfg, lp, ln1)
    if cfg.apply_rotary:
        q = apply_rotary(q, cos, sin, cfg.rotary_interleaved)
        k = apply_rotary(k, cos, sin, cfg.rotary_interleaved)
    attn_impl = attention_fn or packed_attention
    attn = attn_impl(q, k, v, seg_ids, causal=True,
                     scale=_attn_scale(cfg, layer_idx),
                     sliding_window=cfg.sliding_window)
    attn = attn.reshape(*x.shape[:-1], cfg.n_q_heads * cfg.head_dim)
    proj = attn @ lp["attn"]["wo"].astype(x.dtype)
    if "bo" in lp["attn"]:
        proj = proj + lp["attn"]["bo"].astype(x.dtype)
    x = constrain(x + proj)
    ln2 = _norm(cfg, x, lp["ln2"]["scale"], lp["ln2"].get("bias"))
    mlp_out, aux = _mlp_with_aux(cfg, lp, ln2, seg_ids, moe_constraint)
    x = constrain(x + mlp_out)
    return x, (k, v), aux


def positions_from_segments(seg_ids: jnp.ndarray) -> jnp.ndarray:
    """Position of each token within its segment for packed streams.

    [B, L] int32 -> [B, L] int32. Pad tokens get position 0.
    """
    idx = jnp.arange(seg_ids.shape[1], dtype=jnp.int32)[None, :]
    new_seg = jnp.concatenate(
        [jnp.ones_like(seg_ids[:, :1], dtype=bool),
         seg_ids[:, 1:] != seg_ids[:, :-1]], axis=1)
    seg_start = jax.lax.cummax(jnp.where(new_seg, idx, 0), axis=1)
    return (idx - seg_start).astype(jnp.int32)


# ----------------------------------------------------------------------
# Forward (training / prefill)
# ----------------------------------------------------------------------
def forward(
    cfg: TransformerConfig,
    params: Params,
    input_ids: jnp.ndarray,  # [B, L] int32
    seg_ids: jnp.ndarray,    # [B, L] int32; 0 = padding
    positions: Optional[jnp.ndarray] = None,  # [B, L]; default from seg_ids
    *,
    return_kv: bool = False,
    return_aux: bool = False,
    activation_constraint=None,
    attention_fn=None,
    moe_constraint=None,  # models/sharding.py moe_ep_constraint (EP)
    pipeline=None,  # parallel.pipeline.PipelineContext when pp > 1
):
    """Packed forward pass -> final hidden states [B, L, H] (after the
    final norm). Heads are applied separately (`lm_logits`,
    `critic_values`, or fused ops in `realhf_tpu.ops.functional`).

    ``activation_constraint`` is an optional fn applied to the residual
    stream each block (sharding constraints; see models/sharding.py).
    """
    cdt = jnp.dtype(cfg.compute_dtype)
    constrain = activation_constraint or (lambda t: t)
    if positions is None:
        positions = positions_from_segments(seg_ids)

    x = params["embed"]["wte"].astype(cdt)[input_ids]
    if cfg.uses_absolute_position:
        x = x + params["embed"]["wpe"].astype(cdt)[
            positions + cfg.abs_position_embedding_offset]
    if cfg.normalize_embed:
        x = x * jnp.asarray(cfg.hidden_dim ** 0.5, dtype=cdt)
    x = constrain(x)

    if cfg.apply_rotary:
        cos, sin = rotary_freqs(positions, cfg.head_dim, cfg.rotary_base,
                                cfg.rotary_scaling, cfg.rotary_scaling_type,
                                cfg.n_positions)
    else:
        half = cfg.head_dim // 2
        cos = jnp.ones((*positions.shape, half), jnp.float32)
        sin = jnp.zeros((*positions.shape, half), jnp.float32)

    if pipeline is not None and pipeline.n_stages > 1:
        # Pipeline parallelism: blocks are stage-sharded over the
        # "pipe" mesh axis and run as a microbatch-rotation schedule
        # (parallel/pipeline.py). Embedding/rotary above and head/norm
        # below stay GSPMD with pipe-replicated weights.
        assert not return_kv, (
            "KV-cache prefill on a pipeline-parallel mesh is not "
            "supported; allocate generation MFCs on a dp/tp layout "
            "(decoupled allocation).")
        from realhf_tpu.parallel import smap as _smap
        from realhf_tpu.parallel.pipeline import pipeline_blocks

        # Old-jax fallback lowers the pipeline shard_map FULLY manual
        # (parallel/smap.py) -- GSPMD sharding constraints are invalid
        # inside, and semantically no-ops there (the fallback only
        # exists for meshes whose non-pipe axes are trivial).
        pconstrain = constrain if _smap.NEW_SHARD_MAP else (lambda t: t)

        def pblock(lp, layer_idx, carry, seg, cos_, sin_):
            y, _, aux = _block(cfg, lp, layer_idx, carry, seg, cos_,
                               sin_, pconstrain, attention_fn,
                               moe_constraint)
            return y, aux

        # Nested remat for the 1F1B-class memory profile: each block
        # checkpoints its internals AND (pipeline_remat="tick") each
        # tick's whole slab evaluation checkpoints again, so the tick
        # scan's resident residuals are single boundary activations
        # while a tick's backward recompute holds only per-block
        # inputs transiently.
        remat_tick = (cfg.gradient_checkpointing
                      and cfg.pipeline_remat == "tick")
        if cfg.gradient_checkpointing:
            pblock = jax.checkpoint(
                pblock,
                policy=getattr(jax.checkpoint_policies, cfg.remat_policy))

        def block_step(slab, layer_ids, xc, segc, cosc, sinc):
            def body(carry, layer):
                lp, li = layer
                y, aux = pblock(lp, li, carry, segc, cosc, sinc)
                return y, aux
            y, auxs = jax.lax.scan(body, xc, (slab, layer_ids))
            return y, {k: v.sum() for k, v in auxs.items()}

        if getattr(pipeline, "schedule", "gpipe") == "1f1b":
            # Steady-state 1F1B: explicit instruction streams with a
            # custom-VJP backward pipeline and bounded residuals
            # (parallel/schedule.py). Tick-level remat is moot here --
            # the backward already recomputes each stage-tick from its
            # saved boundary input.
            from realhf_tpu.parallel.schedule import pipeline_blocks_1f1b
            x, aux = pipeline_blocks_1f1b(
                pipeline, params["blocks"], cfg.n_layers, x, seg_ids,
                cos, sin, block_step, return_aux=return_aux)
        else:
            x, aux = pipeline_blocks(
                pipeline, params["blocks"], cfg.n_layers, x, seg_ids,
                cos, sin, block_step, return_aux=return_aux,
                remat_tick=remat_tick)
        x = _norm(cfg, x, params["ln_f"]["scale"],
                  params["ln_f"].get("bias"))
        if return_aux:
            return x, None, aux
        return x, None

    def block_fn(lp, layer_idx, carry):
        # cfg/constrain are non-array closures; seg_ids/cos/sin are
        # array closures -- jax.checkpoint differentiates through
        # closed-over arrays correctly.
        return _block(cfg, lp, layer_idx, carry, seg_ids, cos, sin,
                      constrain, attention_fn, moe_constraint)

    if cfg.gradient_checkpointing:
        block_fn = jax.checkpoint(
            block_fn,
            policy=getattr(jax.checkpoint_policies, cfg.remat_policy))

    def scan_body(carry, layer):
        lp, layer_idx = layer
        y, kv, aux = block_fn(lp, layer_idx, carry)
        return y, (kv if return_kv else None,
                   aux if return_aux else None)

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    x, (kvs, auxs) = jax.lax.scan(scan_body, x,
                                  (params["blocks"], layer_ids))
    x = _norm(cfg, x, params["ln_f"]["scale"], params["ln_f"].get("bias"))
    if return_aux:
        aux = {k: v.sum() for k, v in (auxs or {}).items()}
        return x, kvs, aux
    return x, kvs


def lm_logits(cfg: TransformerConfig, params: Params,
              hidden: jnp.ndarray) -> jnp.ndarray:
    """[..., H] -> [..., V] logits in fp32 (tp-padded vocab entries,
    if any, are sliced away so they are never sampled)."""
    w = head_weight(cfg, params)
    logits = jnp.einsum("...h,hv->...v", hidden, w.astype(hidden.dtype),
                        preferred_element_type=jnp.float32)
    if logits.shape[-1] != cfg.vocab_size:
        logits = logits[..., :cfg.vocab_size]
    return logits


def head_weight(cfg: TransformerConfig, params: Params) -> jnp.ndarray:
    if cfg.is_critic:
        return params["head"]["w"]
    if cfg.tied_embedding:
        return params["embed"]["wte"].T
    return params["head"]["w"]


def critic_values(cfg: TransformerConfig, params: Params,
                  hidden: jnp.ndarray) -> jnp.ndarray:
    """[..., H] -> [...] scalar values in fp32."""
    assert cfg.is_critic
    w = params["head"]["w"]
    return jnp.einsum("...h,ho->...o", hidden, w.astype(hidden.dtype),
                      preferred_element_type=jnp.float32)[..., 0]


# ----------------------------------------------------------------------
# KV cache + decode step (generation)
# ----------------------------------------------------------------------
# Cache layout is HEAD-MAJOR: k/v are [nl, B, nkv, S, hd] so the decode
# attention kernel streams a layer's rows straight from HBM with no
# transpose on the hot path. The slot axis is pre-padded to a multiple
# of the kernel's K block so per-token calls never concat-pad.
_CACHE_LEN_MULTIPLE = 128
# Below this depth the decode layer loop is unrolled (static layer
# indices = free views into the stacked cache); deeper models use a
# lax.scan with a scalar-prefetch kernel to keep compile time O(1).
_DECODE_UNROLL_MAX_LAYERS = 48


def round_cache_len(n: int) -> int:
    """Round a KV-cache slot count up to the kernel-friendly multiple."""
    if n <= _CACHE_LEN_MULTIPLE:
        return n
    return -(-n // _CACHE_LEN_MULTIPLE) * _CACHE_LEN_MULTIPLE


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int,
                  dtype=None) -> KVCache:
    """Padded KV cache sized max_prompt_len + max_new_tokens, matching
    reference `prepare_generate_inputs` (real_llm_generate.py:179)."""
    dtype = dtype or jnp.dtype(cfg.compute_dtype)
    max_len = round_cache_len(max_len)
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "valid": jnp.zeros((batch, max_len), bool),
        "length": jnp.zeros((batch,), jnp.int32),
    }


def prefill(cfg: TransformerConfig, params: Params, input_ids: jnp.ndarray,
            seg_ids: jnp.ndarray, positions: Optional[jnp.ndarray] = None,
            *, total_len: Optional[int] = None, activation_constraint=None,
            attention_fn=None,
            moe_constraint=None) -> Tuple[jnp.ndarray, KVCache]:
    """Run the packed forward and materialize a KV cache whose first
    L slots hold the prompt keys/values.

    ``total_len``: allocate the cache at its final decode size
    (prompt + max_new_tokens, rounded up to the kernel block) in ONE
    pad here, instead of a post-hoc `extend_kv_cache` concat copy."""
    hidden, kvs = forward(cfg, params, input_ids, seg_ids, positions,
                          return_kv=True,
                          activation_constraint=activation_constraint,
                          attention_fn=attention_fn,
                          moe_constraint=moe_constraint)
    k, v = kvs  # [nl, B, L, nkv, hd]
    k = k.transpose(0, 1, 3, 2, 4)  # -> [nl, B, nkv, L, hd] head-major
    v = v.transpose(0, 1, 3, 2, 4)
    b, lp = input_ids.shape
    valid = seg_ids != 0
    total = round_cache_len(total_len if total_len is not None else lp)
    pad = total - lp
    if pad:
        widths = [(0, 0), (0, 0), (0, 0), (0, pad), (0, 0)]
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
        valid = jnp.pad(valid, [(0, 0), (0, pad)])
    cache = {
        "k": k,
        "v": v,
        "valid": valid,
        "length": jnp.full((b,), lp, jnp.int32),
    }
    return hidden, cache


def extend_kv_cache(cache: KVCache, extra: int) -> KVCache:
    """Grow the cache along the slot axis by `extra` zero slots.

    Prefer ``prefill(..., total_len=...)`` which allocates the final
    size up front; this concat path remains for incremental callers."""
    nl, b, nkv, s, hd = cache["k"].shape
    new_s = round_cache_len(s + extra)
    extra = new_s - s
    pad = lambda a: jnp.concatenate(
        [a, jnp.zeros((nl, b, nkv, extra, hd), a.dtype)], axis=3)
    return {
        "k": pad(cache["k"]),
        "v": pad(cache["v"]),
        "valid": jnp.concatenate(
            [cache["valid"], jnp.zeros((b, extra), bool)], axis=1),
        "length": cache["length"],
    }


def _stacked_decode_attention(q, k_all, v_all, valid, layer_idx, *,
                              scale, sliding_window, slot, mesh=None):
    """Decode attention against the FULL stacked cache at a traced
    layer index. TPU: scalar-prefetch Pallas kernel (streams exactly
    one layer's rows from HBM, no slice copy), shard_map-partitioned
    over dp x tp meshes. A traced scale (deep
    scale_attn_by_inverse_layer_idx models) pre-multiplies q so the
    kernel still runs with a static scale -- falling back to slicing
    the layer out would re-materialize a full layer-cache copy per
    token, the very bottleneck this kernel removes. The XLA slice
    path remains for CPU tests only."""
    hd = q.shape[-1]
    if pallas_enabled() and hd >= 64:
        from realhf_tpu.ops.decode_attention import run_decode_kernels
        out = run_decode_kernels(
            mesh, q, (k_all, v_all), valid, slot, layer_idx,
            stacked=True, scale=scale, sliding_window=sliding_window)
        if out is not None:
            return out
        # fall through: no kernel partitioning applies; the sliced
        # decode_attention below re-enters the dispatcher flat, gets
        # the same None, and takes its GSPMD-partitioned XLA path
    k_l = jax.lax.dynamic_index_in_dim(k_all, layer_idx, 0, keepdims=False)
    v_l = jax.lax.dynamic_index_in_dim(v_all, layer_idx, 0, keepdims=False)
    return decode_attention(q, k_l, v_l, valid, scale=scale,
                            sliding_window=sliding_window, slot=slot,
                            mesh=mesh)


def decode_step(
    cfg: TransformerConfig,
    params: Params,
    cache: KVCache,
    token: jnp.ndarray,      # [B] int32 -- the token to feed
    positions: jnp.ndarray,  # [B] int32 -- its position in the sequence
    moe_constraint=None,
    uniform_slot: bool = False,
    mesh=None,  # dp x tp mesh: partitions the pallas decode kernels
) -> Tuple[jnp.ndarray, KVCache]:
    """One decode step: feed `token`, return hidden [B, H] for the next
    token's logits and the updated cache. The jitted decode loop built
    on this replaces CUDA-graph decoding (reference
    real_llm_generate.py:214, cuda_graph.py).

    The stacked k/v caches stay whole through the layer loop and only
    the new token's slot is written per layer (`dynamic_update_slice`
    aliases in place inside the decode scan) -- threading them through
    a `lax.scan` as xs/ys would re-materialize the entire cache as a
    fresh stacked output every token, ~3x the roofline's intended HBM
    traffic. Shallow models unroll the layer loop (static layer index
    = free view of the stacked cache); deep models scan with a
    scalar-prefetch attention kernel.

    ``uniform_slot``: promise that every stream writes the SAME cache
    slot (true for the batch generate path, where prefill fills a
    common padded length and all streams advance in lockstep). The
    cache update then lowers to `dynamic_update_slice` instead of a
    per-row scatter -- on a v5e the scatter costs ~0.25 ms per stream
    per step, dominating decode beyond bs~16. Continuous batching
    (per-slot lengths) keeps the scatter path."""
    cdt = jnp.dtype(cfg.compute_dtype)
    b = token.shape[0]
    slot = cache["length"]  # write position per stream

    x = params["embed"]["wte"].astype(cdt)[token]
    if cfg.uses_absolute_position:
        x = x + params["embed"]["wpe"].astype(cdt)[
            positions + cfg.abs_position_embedding_offset]
    if cfg.normalize_embed:
        x = x * jnp.asarray(cfg.hidden_dim ** 0.5, dtype=cdt)

    if cfg.apply_rotary:
        cos, sin = rotary_freqs(positions, cfg.head_dim, cfg.rotary_base,
                                cfg.rotary_scaling, cfg.rotary_scaling_type,
                                cfg.n_positions)
    else:
        half = cfg.head_dim // 2
        cos = jnp.ones((b, half), jnp.float32)
        sin = jnp.zeros((b, half), jnp.float32)

    if uniform_slot:
        s0 = slot[0]
        valid = jax.lax.dynamic_update_slice(
            cache["valid"], jnp.ones((b, 1), bool), (0, s0))
    else:
        valid = cache["valid"].at[jnp.arange(b), slot].set(True)
    new_len = slot + 1

    def layer_body(x, k_all, v_all, lp, layer_idx, static_l=None):
        ln1 = _norm(cfg, x, lp["ln1"]["scale"], lp["ln1"].get("bias"))
        q, k, v = _qkv(cfg, lp, ln1)  # q: [B, nq, hd]; k/v: [B, nkv, hd]
        if cfg.apply_rotary:
            q = apply_rotary(q, cos, sin, cfg.rotary_interleaved)
            k = apply_rotary(k, cos, sin, cfg.rotary_interleaved)
        l = layer_idx if static_l is None else static_l
        if uniform_slot:
            kw = k[None, :, :, None, :].astype(k_all.dtype)  # [1,B,nkv,1,hd]
            vw = v[None, :, :, None, :].astype(v_all.dtype)
            k_all = jax.lax.dynamic_update_slice(k_all, kw, (l, 0, 0, s0, 0))
            v_all = jax.lax.dynamic_update_slice(v_all, vw, (l, 0, 0, s0, 0))
        else:
            k_all = k_all.at[l, jnp.arange(b), :, slot].set(
                k.astype(k_all.dtype))
            v_all = v_all.at[l, jnp.arange(b), :, slot].set(
                v.astype(v_all.dtype))
        base = cfg.head_dim ** -0.5 if cfg.scale_attn_weights else 1.0
        if not cfg.scale_attn_by_inverse_layer_idx:
            scale = base
        elif static_l is not None:
            scale = base / (static_l + 1)
        else:
            scale = _attn_scale(cfg, layer_idx)  # traced scalar
        if static_l is not None:
            attn = decode_attention(q, k_all[static_l], v_all[static_l],
                                    valid, scale=scale,
                                    sliding_window=cfg.sliding_window,
                                    slot=slot, mesh=mesh)
        else:
            attn = _stacked_decode_attention(
                q, k_all, v_all, valid, layer_idx, scale=scale,
                sliding_window=cfg.sliding_window, slot=slot, mesh=mesh)
        proj = attn.reshape(b, -1) @ lp["attn"]["wo"].astype(x.dtype)
        if "bo" in lp["attn"]:
            proj = proj + lp["attn"]["bo"].astype(x.dtype)
        x = x + proj
        ln2 = _norm(cfg, x, lp["ln2"]["scale"], lp["ln2"].get("bias"))
        x = x + _mlp(cfg, lp, ln2, moe_constraint)
        return x, k_all, v_all

    k_all, v_all = cache["k"], cache["v"]
    if cfg.n_layers <= _DECODE_UNROLL_MAX_LAYERS:
        for li in range(cfg.n_layers):
            lp = jax.tree_util.tree_map(lambda a: a[li], params["blocks"])
            x, k_all, v_all = layer_body(x, k_all, v_all, lp, li,
                                         static_l=li)
    else:
        def body(carry, layer):
            xc, kc, vc = carry
            lp, layer_idx = layer
            xc, kc, vc = layer_body(xc, kc, vc, lp, layer_idx)
            return (xc, kc, vc), None

        layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
        (x, k_all, v_all), _ = jax.lax.scan(
            body, (x, k_all, v_all), (params["blocks"], layer_ids))
    x = _norm(cfg, x, params["ln_f"]["scale"], params["ln_f"].get("bias"))
    new_cache = {"k": k_all, "v": v_all, "valid": valid, "length": new_len}
    return x, new_cache
