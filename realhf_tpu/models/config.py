"""Transformer architecture configuration.

Field-level parity with reference ``realhf/api/core/model_api.py:144``
(ReaLModelConfig): one config class describes every supported family
(llama/qwen2/mistral/gpt2/gemma/mixtral, actor or critic). The critic
variant replaces the LM head with a scalar value head (`is_critic`).
"""

import dataclasses
from typing import Optional


@dataclasses.dataclass
class MoEConfig:
    """Mixture-of-experts settings (reference ``ReaLMoEConfig``)."""
    num_experts: int = 8
    top_k: int = 2
    routing_type: str = "aux_loss"  # aux_loss | sinkhorn | none
    aux_loss_coeff: float = 1e-3
    z_loss_coeff: float = 0.0
    input_jitter_eps: Optional[float] = None
    capacity_factor: Optional[float] = None
    use_grouped_gemm: bool = True
    # Real expert parallelism (exceeds the reference, whose dispatcher
    # says "Currently does not support expert parallel",
    # token_dispatcher.py:26-27): shard the expert (E) dim of the
    # stacked expert weights over the "data" mesh axis. The GShard
    # dispatch einsums then become all-to-alls inserted by GSPMD:
    # tokens sharded by data are exchanged for experts sharded by
    # data. Requires num_experts % data_parallel_size == 0 and the
    # capacity or dense dispatch mode (ragged grouped GEMMs cannot
    # shard the group dim).
    expert_parallel: bool = False


@dataclasses.dataclass
class TransformerConfig:
    """Architecture of one decoder-only transformer.

    Mirrors `ReaLModelConfig` (reference model_api.py:144-294) field by
    field; TPU-specific additions at the bottom control dtypes and
    rematerialization.
    """

    n_layers: int
    n_kv_heads: int
    n_q_heads: int
    hidden_dim: int
    intermediate_dim: int
    vocab_size: int
    head_dim: Optional[int] = None
    n_positions: Optional[int] = None
    embd_pdrop: float = 0.0
    resid_pdrop: float = 0.0
    attn_pdrop: float = 0.0
    layer_norm_epsilon: float = 1e-5
    activation_function: str = "gelu"  # gelu | gelu_new | silu
    scale_attn_by_inverse_layer_idx: bool = False
    scale_attn_weights: bool = True
    use_attention_bias: bool = True
    use_attn_proj_bias: bool = True
    use_mlp_bias: bool = True
    layer_norm_type: Optional[str] = None  # None (LayerNorm) | "rms" | "gemma"
    mlp_type: Optional[str] = None  # None (plain 2-mat MLP) | "llama" | "moe"
    # rotary embedding
    apply_rotary: bool = False
    rotary_base: float = 10000.0
    rotary_interleaved: bool = False
    rotary_scaling: Optional[float] = None
    rotary_scaling_type: Optional[str] = None  # "linear" | "dynamic"
    # gemma
    normalize_embed: bool = False
    # opt-style absolute position embedding offset
    abs_position_embedding_offset: int = 0
    do_layernorm_before: bool = True
    tied_embedding: bool = False
    sliding_window: Optional[int] = None
    moe: Optional[MoEConfig] = None
    is_critic: bool = False

    # --- TPU-native additions -----------------------------------------
    # Numerics: params kept in param_dtype; matmuls run in compute_dtype
    # (bf16 feeds the MXU); softmax/normalization accumulate in fp32.
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    # Rematerialize each block in backward (jax.checkpoint over the
    # layer scan) -- the reference's gradient_checkpointing flag.
    gradient_checkpointing: bool = False
    # jax.checkpoint_policies name used when gradient_checkpointing is
    # on. "nothing_saveable" = full recompute (min memory);
    # "dots_with_no_batch_dims_saveable" keeps matmul outputs (more
    # HBM, measurably faster when the model fits).
    remat_policy: str = "nothing_saveable"
    # Pipeline-parallel remat granularity when gradient_checkpointing:
    # "tick" rematerializes each whole stage-slab evaluation, making
    # resident pipeline activations depth-independent (the 1F1B-class
    # memory profile; reference TrainSchedule static_schedule.py:319);
    # "block" keeps the per-block checkpoint of the non-pipeline path.
    pipeline_remat: str = "tick"

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_dim // self.n_q_heads
        assert self.n_q_heads % self.n_kv_heads == 0, \
            (self.n_q_heads, self.n_kv_heads)
        if self.mlp_type == "moe":
            assert self.moe is not None
        if self.rotary_scaling_type is not None:
            if self.rotary_scaling is None:
                raise ValueError(
                    "rotary_scaling must be set when rotary_scaling_type is.")
            if self.rotary_scaling_type == "dynamic" and self.n_positions is None:
                raise ValueError(
                    "dynamic NTK rotary scaling requires n_positions.")

    @property
    def uses_absolute_position(self) -> bool:
        return not self.apply_rotary

    @property
    def gated_mlp(self) -> bool:
        return self.mlp_type in ("llama", "moe")

    def n_params(self) -> int:
        """Approximate dense parameter count (for FLOPs/memory estimates)."""
        h, f, v = self.hidden_dim, self.intermediate_dim, self.vocab_size
        attn = h * (self.n_q_heads + 2 * self.n_kv_heads) * self.head_dim \
            + self.n_q_heads * self.head_dim * h
        mlp = (3 if self.gated_mlp else 2) * h * f
        if self.mlp_type == "moe":
            mlp *= self.moe.num_experts
        embed = v * h if self.tied_embedding else 2 * v * h
        if self.is_critic:
            embed = v * h + h
        return self.n_layers * (attn + mlp) + embed
