"""GSPMD sharding rules for the transformer.

TPU-native replacement for the reference's Megatron-derived TP/SP
modules (``realhf/impl/model/parallelism/model_parallel/modules.py``,
``mappings.py``): instead of hand-written column/row-parallel linears
and scatter/gather autograd functions, every parameter gets a
`PartitionSpec` and XLA inserts the collectives.

Mapping (reference module -> spec here):
- ParallelEmbedding (vocab-partitioned, modules.py:53)  -> wte P("model", None)
- ColumnParallelLinear (modules.py:727)                 -> wq/wk/wv/wg/wu P(..., "model")
- RowParallelLinear (modules.py:875)                    -> wo/wd P(..., "model", None)
- parallel_lm_logits + _VocabParallelCrossEntropy       -> head P(None, "model") + chunked CE in ops/functional.py
- sequence parallel scatter/gather (mappings.py:207-294)-> residual-stream
  constraint P("data", "model", None): XLA materializes the
  all-gather before attention/MLP and reduce-scatter after, which is
  exactly Megatron-SP's communication pattern.
"""

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.parallel.mesh import CTX_AXIS, DATA_AXIS, MODEL_AXIS, PIPE_AXIS


def param_pspecs(cfg: TransformerConfig,
                 pipeline_parallel: bool = False) -> Dict[str, Any]:
    """PartitionSpec pytree congruent with ``init_params`` output.

    With ``pipeline_parallel`` the stacked-block leading (layer) dim is
    sharded over the "pipe" axis -- each stage owns a contiguous
    n_layers/pp slab (the reference's partition_pipeline_layers split,
    real_llm_parallel.py:342); embedding/head/final-norm stay
    pipe-replicated and run outside the pipeline loop.
    """
    lead = PIPE_AXIS if pipeline_parallel else None
    col = P(lead, None, MODEL_AXIS)      # [nl, H, out_sharded]
    row = P(lead, MODEL_AXIS, None)      # [nl, in_sharded, H]
    col_b = P(lead, MODEL_AXIS)          # bias of a column-parallel linear
    rep2 = P(lead, None)                 # [nl, H] replicated over tp
    specs: Dict[str, Any] = {
        "embed": {"wte": P(MODEL_AXIS, None)},
        "blocks": {
            "ln1": {"scale": rep2},
            "attn": {"wq": col, "wk": col, "wv": col, "wo": row},
            "ln2": {"scale": rep2},
            "mlp": {},
        },
        "ln_f": {"scale": P(None)},
    }
    if cfg.uses_absolute_position:
        specs["embed"]["wpe"] = P(None, None)
    mlp = specs["blocks"]["mlp"]
    if cfg.mlp_type == "moe":
        # Experts TP-sharded (reference behavior: each expert's MLP is
        # column/row-parallel, experts.py:26). With expert_parallel the
        # E dim additionally shards over the data axis (real EP -- the
        # reference's dispatcher explicitly does not support it,
        # token_dispatcher.py:26-27).
        ep = DATA_AXIS if (cfg.moe is not None
                           and cfg.moe.expert_parallel) else None
        mlp["router"] = P(lead, None, None)
        mlp["wg"] = P(lead, ep, None, MODEL_AXIS)
        mlp["wu"] = P(lead, ep, None, MODEL_AXIS)
        mlp["wd"] = P(lead, ep, MODEL_AXIS, None)
    elif cfg.gated_mlp:
        mlp["wg"] = col
        mlp["wu"] = col
        mlp["wd"] = row
    else:
        mlp["wu"] = col
        mlp["wd"] = row
    if cfg.use_attention_bias:
        a = specs["blocks"]["attn"]
        a["bq"], a["bk"], a["bv"] = col_b, col_b, col_b
    if cfg.use_attn_proj_bias:
        specs["blocks"]["attn"]["bo"] = rep2
    if cfg.use_mlp_bias and cfg.mlp_type is None:
        mlp["bu"] = col_b
        mlp["bd"] = rep2
    if cfg.layer_norm_type is None:
        specs["blocks"]["ln1"]["bias"] = rep2
        specs["blocks"]["ln2"]["bias"] = rep2
        specs["ln_f"]["bias"] = P(None)
    if cfg.is_critic:
        specs["head"] = {"w": P(None, None)}
    elif not cfg.tied_embedding:
        specs["head"] = {"w": P(None, MODEL_AXIS)}
    return specs


def padded_vocab_size(cfg: TransformerConfig, tp: int) -> int:
    """Vocab padded up to a tp multiple (Megatron's VocabUtility,
    reference model_parallel/utils.py:154)."""
    return ((cfg.vocab_size + tp - 1) // tp) * tp


def pad_vocab(cfg: TransformerConfig, params: Dict[str, Any],
              tp: int) -> Dict[str, Any]:
    """Zero-pad the vocab dim of wte/head so it shards over tp.
    Consumers slice logits back to cfg.vocab_size (lm_logits etc.), so
    padded entries are never sampled or normalized over."""
    import numpy as np
    vp = padded_vocab_size(cfg, tp)
    v = cfg.vocab_size
    if vp == v or params["embed"]["wte"].shape[0] == vp:  # already padded
        return params
    xp = jax.numpy if hasattr(params["embed"]["wte"], "devices") else np

    def _pad(a, axis):
        width = [(0, 0)] * a.ndim
        width[axis] = (0, vp - v)
        return xp.pad(a, width)

    params = {**params, "embed": {**params["embed"]}}
    params["embed"]["wte"] = _pad(params["embed"]["wte"], 0)
    if not cfg.is_critic and not cfg.tied_embedding:
        params = {**params, "head": {"w": _pad(params["head"]["w"], 1)}}
    return params


def normalize_vocab_padding(cfg: TransformerConfig, params: Dict[str, Any],
                            tp: int) -> Dict[str, Any]:
    """Re-pad params (possibly padded for a different tp) to the
    padding this tp needs."""
    return pad_vocab(cfg, unpad_vocab(cfg, params), tp)


def unpad_vocab(cfg: TransformerConfig, params: Dict[str, Any]
                ) -> Dict[str, Any]:
    """Inverse of pad_vocab (checkpoint saving)."""
    v = cfg.vocab_size
    if params["embed"]["wte"].shape[0] == v:
        return params
    params = {**params, "embed": {**params["embed"]}}
    params["embed"]["wte"] = params["embed"]["wte"][:v]
    if not cfg.is_critic and not cfg.tied_embedding:
        params = {**params, "head": {"w": params["head"]["w"][:, :v]}}
    return params


def repad_vocab_leaf(cfg: TransformerConfig, path, arr, target_tp: int):
    """Per-LEAF form of unpad_vocab+pad_vocab for streamed installs
    (parallel/realloc.py:install_param_chunks): the single place the
    which-leaves-carry-vocab rule lives, congruent with the tree forms
    above. ``path`` is the leaf's key tuple, e.g. ("embed", "wte")."""
    import numpy as np
    vp = padded_vocab_size(cfg, target_tp)
    v = cfg.vocab_size
    if path == ("embed", "wte"):
        arr = arr[:v]
        if vp != v:
            arr = np.pad(arr, [(0, vp - v)] + [(0, 0)] * (arr.ndim - 1))
    elif (path == ("head", "w") and not cfg.is_critic
            and not cfg.tied_embedding):
        arr = arr[:, :v]
        if vp != v:
            arr = np.pad(arr, [(0, 0), (0, vp - v)])
    return arr


def param_shardings(cfg: TransformerConfig, mesh: Mesh) -> Dict[str, Any]:
    pp = mesh.shape.get(PIPE_AXIS, 1)
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        param_pspecs(cfg, pipeline_parallel=pp > 1),
                        is_leaf=lambda x: isinstance(x, P))


def zero1_moment_spec(spec: P, shape, dp: int) -> P:
    """Extend a parameter's PartitionSpec with the DATA axis on its
    largest free dim -- the ZeRO-1 sharding for that parameter's
    optimizer moments (reference Megatron DistributedOptimizer,
    backend/megatron.py:823-940: fp32 m/v sharded over DP). The
    all-gather of the parameter update that ZeRO-1 performs is inserted
    by GSPMD when `optax.apply_updates` output reshards to the param's
    own spec."""
    if dp <= 1:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    used = set()
    for e in entries:
        if e is None:
            continue
        for ax in (e if isinstance(e, tuple) else (e,)):
            used.add(ax)
    if DATA_AXIS in used:  # e.g. expert-parallel MoE weights
        return spec
    best_i, best = None, 0
    for i, (e, d) in enumerate(zip(entries, shape)):
        if e is None and d % dp == 0 and d > best:
            best, best_i = d, i
    if best_i is None:
        return spec
    entries[best_i] = DATA_AXIS
    return P(*entries)


def opt_state_shardings(opt_state_shape, cfg: TransformerConfig,
                        mesh: Mesh, zero1: bool = True):
    """NamedSharding pytree for an optax state (from
    ``jax.eval_shape(tx.init, params)``).

    Moment leaves are recognized by path suffix: optax states embed
    ``mu``/``nu`` (and any other per-parameter slot) as pytrees
    congruent with the params, so a state leaf whose key-path ends with
    a full parameter path IS that parameter's slot and gets the
    parameter's spec -- extended over the DATA axis when ``zero1``.
    Everything else (step counts, scalars) is replicated."""
    pp = mesh.shape.get(PIPE_AXIS, 1)
    dp = mesh.shape.get(DATA_AXIS, 1) if zero1 else 1
    pspecs = param_pspecs(cfg, pipeline_parallel=pp > 1)
    flat_p = jax.tree_util.tree_flatten_with_path(
        pspecs, is_leaf=lambda x: isinstance(x, P))[0]
    param_paths = [(tuple(str(k) for k in path), spec)
                   for path, spec in flat_p]

    def assign(path, leaf):
        strs = tuple(str(k) for k in path)
        for ppath, spec in param_paths:
            if len(strs) >= len(ppath) and strs[-len(ppath):] == ppath:
                if leaf.shape != ():
                    return NamedSharding(
                        mesh, zero1_moment_spec(spec, leaf.shape, dp))
                break
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(assign, opt_state_shape)


def batch_pspec() -> P:
    """[B, L] token/segment arrays: DP over streams, context
    parallelism over the sequence dim."""
    return P(DATA_AXIS, CTX_AXIS)


def residual_pspec(sequence_parallel: bool) -> P:
    """[B, L, H] residual stream; with SP the sequence dim is also
    sharded over the TP axis (Megatron-SP analog)."""
    if sequence_parallel:
        return P(DATA_AXIS, (CTX_AXIS, MODEL_AXIS), None)
    return P(DATA_AXIS, CTX_AXIS, None)


def activation_constraint(mesh: Mesh, sequence_parallel: bool):
    """The per-block residual-stream constraint fed to
    ``transformer.forward(activation_constraint=...)``."""
    sharding = NamedSharding(mesh, residual_pspec(sequence_parallel))

    def constrain(x):
        return jax.lax.with_sharding_constraint(x, sharding)

    return constrain


def moe_ep_constraint(cfg: TransformerConfig, mesh: Mesh):
    """Constraint pinning expert-major ``[E, ...]`` MoE intermediates
    to the data axis when expert parallelism is on -- this is what
    turns the GShard dispatch/combine einsums into all-to-alls instead
    of letting XLA all-gather the expert weights. Returns None for
    non-EP configs (the common case)."""
    if not (cfg.mlp_type == "moe" and cfg.moe is not None
            and cfg.moe.expert_parallel):
        return None

    def constrain(x):
        spec = P(DATA_AXIS, *([None] * (x.ndim - 1)))
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec))

    return constrain


