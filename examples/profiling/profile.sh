#!/bin/bash
# Sweep-file profiling workflow (reference examples/profiling parity).
#
# Each jsonl line is a dict of dotted overrides on the `profile`
# experiment (the 6-MFC PPO graph on synthetic data,
# realhf_tpu/experiments/profile_exp.py). One format covers what the
# reference splits across allocations/datasets/interfaces/models
# sweep files -- see the samples next to this script.
#
# REALHF_TPU_DUMP_TRACE=1 dumps a jax.profiler trace per MFC;
# REALHF_TPU_DUMP_MEMORY=1 dumps device memory profiles
# (base/monitor.py). On a machine without TPUs, prepend
#   JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
# to sweep layouts on the virtual mesh (timings then rank CPU cost,
# not TPU cost; run on the chip for real numbers).
#
# A single setup (no sweep) runs through quickstart directly:
#   python -m realhf_tpu.apps.quickstart profile \
#       model_size=1b benchmark_steps=3 actor_gen_alloc=d8t1

set -e
cd "$(dirname "$0")/../.."

REALHF_TPU_DUMP_TRACE=${REALHF_TPU_DUMP_TRACE:-0} \
python scripts/profile_sweep.py \
    --sweep examples/profiling/allocations.jsonl \
    --out profile_results.jsonl \
    model_size=${MODEL_SIZE:-125m} \
    benchmark_steps=${BENCHMARK_STEPS:-3} \
    n_prompts=64 \
    dataset.train_bs_n_seqs=16 \
    ppo.max_new_tokens=64 ppo.min_new_tokens=64
