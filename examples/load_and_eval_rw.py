"""Example: load a trained reward model and score prompt+answer pairs.

TPU-native counterpart of the reference's ``examples/load_and_eval_rw.py``:
read a reward checkpoint saved by the ``rw`` experiment (HF layout with
a scalar value head, ``models/hf/registry.py`` save path), build an
inference Engine over the local devices, and print a score per record
of a prompt-answer JSONL.

Run::

    PYTHONPATH=. python examples/load_and_eval_rw.py \
        <checkpoint_dir> <data.jsonl> [tokenizer_path]

With no arguments it self-demonstrates on a random-init tiny critic
and synthetic token data (useful as a smoke test on the 8-device CPU
mesh).
"""

import json
import sys

import numpy as np

import jax

from realhf_tpu.api.config import ModelName
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.api import model as model_api
from realhf_tpu.engine.engine import Engine
from realhf_tpu.interfaces.rw import PairedRewardInterface
from realhf_tpu.models import transformer as T
from realhf_tpu.parallel.mesh import MeshContext, ParallelismConfig, make_mesh


def build_engine(cfg, params):
    n = len(jax.devices())
    tp = 1
    while (tp < n and n % (tp * 2) == 0
           and cfg.n_q_heads % (tp * 2) == 0):
        tp *= 2
    par = ParallelismConfig(data_parallel_size=n // tp,
                            tensor_parallel_size=tp)
    ctx = MeshContext(ModelName("reward", 0), make_mesh(par), par)
    return Engine(cfg, ctx, params)


def score(engine, token_seqs):
    """One scalar per sequence: the value head at the final token."""
    model = model_api.Model(ModelName("reward", 0), engine, None)
    seqlens = [len(s) for s in token_seqs]
    batch = SequenceSample.from_default(
        ids=list(range(len(token_seqs))), seqlens=seqlens,
        data=dict(packed_input_ids=np.concatenate(token_seqs)
                  .astype(np.int32)))
    out = PairedRewardInterface(enable_save=False).inference(model, batch)
    return np.asarray(out.data["rewards"])


def main():
    if len(sys.argv) >= 3:
        from transformers import AutoTokenizer

        from realhf_tpu.models.hf.registry import load_hf_checkpoint
        ckpt, data_path = sys.argv[1], sys.argv[2]
        tok = AutoTokenizer.from_pretrained(
            sys.argv[3] if len(sys.argv) > 3 else ckpt)
        cfg, params = load_hf_checkpoint(ckpt, is_critic=True)
        records = [json.loads(l) for l in open(data_path)]
        seqs = [np.asarray(
            tok(r["prompt"] + r["answer"])["input_ids"], np.int32)
            for r in records]
        engine = build_engine(cfg, params)
        for r, s in zip(records, score(engine, seqs)):
            print(f"{s:+.4f}  id={r.get('id')}")
        return

    # Self-demo: random-init tiny critic + synthetic sequences.
    from realhf_tpu.models.config import TransformerConfig
    cfg = TransformerConfig(
        n_layers=2, n_kv_heads=2, n_q_heads=4, hidden_dim=32,
        intermediate_dim=64, vocab_size=128, apply_rotary=True,
        layer_norm_type="rms", mlp_type="llama",
        use_attention_bias=False, use_attn_proj_bias=False,
        use_mlp_bias=False, activation_function="silu",
        compute_dtype="float32", is_critic=True)
    params = T.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    seqs = [rng.integers(1, 120, size=(int(l),)).astype(np.int32)
            for l in rng.integers(5, 20, size=(6,))]
    engine = build_engine(cfg, params)
    scores = score(engine, seqs)
    assert scores.shape == (6,) and np.isfinite(scores).all()
    for i, s in enumerate(scores):
        print(f"{s:+.4f}  seq{i} len={len(seqs[i])}")
    print("OK (random-init demo)")


if __name__ == "__main__":
    main()
