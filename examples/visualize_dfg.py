"""Example: inspect and export an experiment's dataflow graph.

TPU-native counterpart of the reference's ``examples/visualize_dfg.py``:
build any experiment config, walk its MFC graph (nodes = model
function calls, edges = data keys), print a topological summary, and
emit a Graphviz DOT file you can render with ``dot -Tpng``.

Run::

    PYTHONPATH=. python examples/visualize_dfg.py [out.dot]
"""

import sys

from realhf_tpu.api.dfg import DFG
from realhf_tpu.experiments.ppo_exp import PPOConfig


def describe(dfg: DFG) -> str:
    lines = []
    for node in dfg.topological_order():
        src = " (source)" if node.is_src else ""
        dst = " (sink)" if node.is_dst else ""
        lines.append(f"{node.name}{src}{dst}: role={node.role} "
                     f"type={node.interface_type.value}")
        for parent in node.parents:
            shared = set(node.input_keys) & set(parent.output_keys)
            lines.append(f"    <- {parent.name} [{', '.join(sorted(shared))}]")
    return "\n".join(lines)


def to_dot(dfg: DFG) -> str:
    out = ["digraph dfg {", "  rankdir=LR;"]
    for node in dfg.nodes:
        shape = {"generate": "cds", "inference": "ellipse",
                 "train_step": "box"}[node.interface_type.value]
        out.append(f'  "{node.name}" [shape={shape}, '
                   f'label="{node.name}\\n{node.role}"];')
    for node in dfg.nodes:
        for parent in node.parents:
            shared = set(node.input_keys) & set(parent.output_keys)
            out.append(f'  "{parent.name}" -> "{node.name}" '
                       f'[label="{", ".join(sorted(shared))}"];')
    out.append("}")
    return "\n".join(out)


def main():
    spec = PPOConfig(experiment_name="vis", trial_name="t0").build()
    dfg = DFG(spec.mfcs)
    print(describe(dfg))
    path = sys.argv[1] if len(sys.argv) > 1 else "dfg.dot"
    with open(path, "w") as f:
        f.write(to_dot(dfg) + "\n")
    print(f"\nDOT written to {path} (render: dot -Tpng {path} -o dfg.png)")


if __name__ == "__main__":
    main()
