"""Example: PPO with an EMA reference model instead of a frozen one.

TPU-native counterpart of the reference's
``examples/customized_exp/ppo_ref_ema.py``: the KL-penalty reference
model becomes a REPLICA of the actor role whose weights EMA-track the
actor through the parameter-reallocation hook
(``target = eta * actor + (1 - eta) * target``, ParamReallocHook.eta;
reference ``patch_reparallelization`` real_llm_api.py:762). No
framework fork: build the stock PPO spec, repoint the ``ref_inf`` MFC
at the actor role with its own layout, attach the hook, drop the
now-unused "ref" model.

Run (self-demo on the virtual mesh)::

    PYTHONPATH=. JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/ppo_ref_ema.py
"""

import json
import os
import tempfile

import numpy as np

from realhf_tpu.api.config import ModelName
from realhf_tpu.api.dfg import ParamReallocHook
from realhf_tpu.base.testing import IntegerTokenizer
from realhf_tpu.engine.optim import OptimizerConfig
from realhf_tpu.experiments.common import apply_overrides
from realhf_tpu.experiments.ppo_exp import PPOConfig
from realhf_tpu.parallel.mesh import ParallelismConfig

TINY = dict(n_layers=2, n_kv_heads=2, n_q_heads=4, hidden_dim=32,
            intermediate_dim=64, vocab_size=1100, apply_rotary=True,
            layer_norm_type="rms", mlp_type="llama",
            use_attention_bias=False, use_attn_proj_bias=False,
            use_mlp_bias=False, activation_function="silu")


def ema_ref_spec(cfg: PPOConfig, eta: float = 0.5):
    """Build the PPO spec, then rewire ref_inf as an EMA actor replica."""
    spec = cfg.build()
    ref_inf = next(n for n in spec.mfcs if n.name == "ref_inf")
    # the reference model IS the actor role, replica 1: a second weight
    # copy on its own layout, refreshed by the realloc pre-hook
    ref_inf.model_name = ModelName("actor", 1)
    del spec.models["ref"]
    ref_inf.add_pre_hook(
        ParamReallocHook(source=ModelName("actor", 0), eta=eta))
    return spec


def main():
    tmp = tempfile.mkdtemp()
    rng = np.random.default_rng(3)
    path = os.path.join(tmp, "prompts.jsonl")
    with open(path, "w") as f:
        for i in range(16):
            f.write(json.dumps(
                {"id": i, "prompt": " ".join(
                    f"w{int(x)}" for x in rng.integers(0, 50, 4))}) + "\n")

    cfg = PPOConfig(experiment_name="ppoema", trial_name="t0",
                    total_train_epochs=1, benchmark_steps=2,
                    # EMA replica layout: differs from the actor primary
                    # so the runtime materializes a real replica engine
                    ref_inf_alloc="d2t4")
    apply_overrides(cfg, {
        "dataset.path": path,
        "dataset.train_bs_n_seqs": "8",
        "dataset.max_seqlen": "16",
        "ppo.max_new_tokens": "8",
        "ppo.min_new_tokens": "1",
        "ppo.ppo_n_minibatches": "2",
        "ppo.kl_ctl": "0.1",
    })
    spec = ema_ref_spec(cfg, eta=0.5)
    for role, mspec in spec.models.items():
        mspec.path = None
        mspec.random_init_config = dict(TINY)
        mspec.bf16 = False
        mspec.parallel = ParallelismConfig(data_parallel_size=4,
                                           tensor_parallel_size=2)
        if mspec.optimizer is not None:
            mspec.optimizer = OptimizerConfig(
                lr=1e-3, warmup_steps_proportion=0.0,
                lr_scheduler_type="constant")
    spec.tokenizer = IntegerTokenizer(vocab_size=1000)

    from realhf_tpu.system.inline import InlineRunner
    runner = InlineRunner(spec)
    stats = runner.run()
    assert np.isfinite(stats["actor_train"]["actor_loss"])
    assert np.isfinite(stats["actor_train"]["kl_reward"])
    # the EMA replica engine exists and tracked at least one refresh
    assert "ref_inf" in runner.host.replicas
    print("OK: PPO ran with an EMA (eta=0.5) actor-replica reference; "
          f"kl_reward={stats['actor_train']['kl_reward']:+.5f}")


if __name__ == "__main__":
    main()
