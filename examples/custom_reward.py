"""Example: a custom rule-based reward interface.

TPU-native counterpart of the reference's customized-experiment
example (``examples/customized_exp/ppo_sentiment.py``): instead of a
reward MODEL, score sequences with arbitrary Python (here: fraction of
response tokens equal to a target token). Register it under a name and
point any experiment's reward MFC at it -- no framework fork needed.

Use from the CLI via user-code injection::

    REALHF_TPU_PACKAGE_PATH=examples/custom_reward.py \
        python -m realhf_tpu.apps.quickstart ppo ... \
        # then override the reward MFC interface in a custom experiment
"""

import dataclasses
from typing import Optional

import numpy as np

from realhf_tpu.api import model as model_api
from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base.datapack import flat2d


@dataclasses.dataclass
class TokenCountReward(model_api.ModelInterface):
    """Reward = fraction of non-prompt tokens equal to ``target_token``
    (a stand-in for any rule-based / external scorer: sentiment
    classifier, verifier, unit-test runner, ...). Needs no model
    forward at all -- the reward "model" role can be a tiny stub."""

    target_token: int = 10
    scale: float = 1.0

    def inference(self, model: model_api.Model, input_: SequenceSample,
                  n_mbs: Optional[int] = None) -> SequenceSample:
        seqlens = flat2d(input_.seqlens["packed_input_ids"])
        ids = np.asarray(input_.data["packed_input_ids"])
        pm = input_.data.get("prompt_mask")
        pm = (np.asarray(pm, bool) if pm is not None
              else np.zeros_like(ids, bool))
        rewards, off = [], 0
        for l in seqlens:
            tok = ids[off:off + l]
            keep = ~pm[off:off + l]
            denom = max(int(keep.sum()), 1)
            rewards.append(
                self.scale * float((tok[keep] == self.target_token).sum())
                / denom)
            off += l
        nested = [[1] * len(lens)
                  for lens in input_.seqlens["packed_input_ids"]]
        with SequenceSample.disable_validation():
            return SequenceSample(
                keys=["rewards"],
                trailing_shapes=dict(rewards=()),
                dtypes=dict(rewards=np.float32),
                ids=list(input_.ids),
                seqlens=dict(rewards=nested),
                data=dict(rewards=np.asarray(rewards, np.float32)),
                metadata={})


model_api.register_interface("token_count_reward", TokenCountReward)
