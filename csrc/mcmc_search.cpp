// MCMC search over per-MFC (device slice x parallel layout) assignments.
//
// Native counterpart of the reference's C++ search module
// (csrc/search/search.cpp: mdm_search/multi_mcmc_search) rebuilt for a
// TPU cost model: Python enumerates, per MFC, candidate placements
// (contiguous chip slice + layout) with pre-estimated execution times
// and pairwise parameter-reallocation times per role; this module runs
// simulated annealing over candidate indices, scoring each assignment
// by simulating the dataflow graph (list scheduling: an MFC starts
// when its dependencies finished AND its chips are free; same-role
// layout changes pay the realloc cost), and returns the best
// assignment found.
//
// Exposed through a plain C ABI for ctypes (no pybind11 in the image).
//
// Layout of the flattened inputs (n = #MFCs, m = #candidates total):
//   cand_offsets[n+1]       : MFC i's candidates are [cand_offsets[i],
//                             cand_offsets[i+1]) in the arrays below
//   cand_dev_lo / dev_hi[m] : chip slice [lo, hi) of each candidate
//   cand_time[m]            : execution seconds of each candidate
//   roles[n]                : role id per MFC (realloc accounting)
//   trainable[n]            : 1 if the MFC trains its role
//   deps[n*n]               : deps[i*n+j] = 1 iff j must finish before i
//   realloc_time[m*m]       : seconds to move role weights between the
//                             layouts of candidates a and b (0 = free);
//                             only consulted for same-role transitions

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

namespace {

struct Problem {
  int n_mfcs;
  int n_devices;
  const int64_t* cand_offsets;
  const int32_t* cand_dev_lo;
  const int32_t* cand_dev_hi;
  const double* cand_time;
  const int32_t* roles;
  const int32_t* trainable;
  const int8_t* deps;
  const double* realloc_time;
  int64_t n_cands;
};

// Simulate one training step of the DFG under an assignment.
// Greedy list scheduling in topological order of ready times.
double simulate(const Problem& p, const std::vector<int64_t>& pick) {
  const int n = p.n_mfcs;
  std::vector<double> finish(n, -1.0);
  std::vector<double> dev_free(p.n_devices, 0.0);
  // Where each role's weights currently live (candidate index of the
  // last MFC that used them); -1 = resident at the trainable layout.
  std::vector<int> done(n, 0);
  int n_done = 0;

  // role -> candidate index of its trainable ("home") layout, if any
  std::vector<int64_t> home(n, -1);
  for (int i = 0; i < n; ++i) {
    if (p.trainable[i]) {
      for (int j = 0; j < n; ++j)
        if (p.roles[j] == p.roles[i]) home[j] = pick[i];
    }
  }

  while (n_done < n) {
    // pick the ready MFC with the earliest possible start
    int best = -1;
    double best_start = 1e30;
    for (int i = 0; i < n; ++i) {
      if (done[i]) continue;
      bool ready = true;
      double dep_t = 0.0;
      for (int j = 0; j < n; ++j) {
        if (p.deps[(size_t)i * n + j]) {
          if (!done[j]) { ready = false; break; }
          dep_t = std::max(dep_t, finish[j]);
        }
      }
      if (!ready) continue;
      const int64_t c = pick[i];
      double dev_t = 0.0;
      for (int d = p.cand_dev_lo[c]; d < p.cand_dev_hi[c]; ++d)
        dev_t = std::max(dev_t, dev_free[d]);
      const double start = std::max(dep_t, dev_t);
      if (start < best_start) { best_start = start; best = i; }
    }
    if (best < 0) return 1e30;  // cyclic deps: reject
    const int64_t c = pick[best];
    double cost = p.cand_time[c];
    // weights arrive from the role's home layout when they differ
    if (home[best] >= 0 && home[best] != c)
      cost += p.realloc_time[(size_t)home[best] * p.n_cands + c];
    // a trained role must return its weights home afterwards; the
    // reverse realloc is charged to the consumer side above, so only
    // charge the forward move here.
    const double end = best_start + cost;
    finish[best] = end;
    for (int d = p.cand_dev_lo[c]; d < p.cand_dev_hi[c]; ++d)
      dev_free[d] = end;
    done[best] = 1;
    ++n_done;
  }
  double mk = 0.0;
  for (int i = 0; i < n; ++i) mk = std::max(mk, finish[i]);
  return mk;
}

}  // namespace

extern "C" {

// Returns the best simulated step time; writes the chosen candidate
// index per MFC into out_pick[n_mfcs].
double mcmc_search(
    int n_mfcs, int n_devices,
    const int64_t* cand_offsets,
    const int32_t* cand_dev_lo, const int32_t* cand_dev_hi,
    const double* cand_time,
    const int32_t* roles, const int32_t* trainable,
    const int8_t* deps,
    const double* realloc_time, int64_t n_cands,
    int64_t n_steps, double beta0, double beta1, uint64_t seed,
    int64_t* out_pick) {
  Problem p{n_mfcs, n_devices, cand_offsets, cand_dev_lo, cand_dev_hi,
            cand_time, roles, trainable, deps, realloc_time, n_cands};
  std::mt19937_64 rng(seed);

  std::vector<int64_t> pick(n_mfcs);
  for (int i = 0; i < n_mfcs; ++i) pick[i] = cand_offsets[i];
  // Trainable MFCs of a role and their home layout interact; start
  // from the first candidate everywhere, then anneal.
  double cur = simulate(p, pick);
  std::vector<int64_t> best_pick = pick;
  double best = cur;

  std::uniform_int_distribution<int> pick_mfc(0, n_mfcs - 1);
  std::uniform_real_distribution<double> unif(0.0, 1.0);

  for (int64_t step = 0; step < n_steps; ++step) {
    const int i = pick_mfc(rng);
    const int64_t lo = cand_offsets[i], hi = cand_offsets[i + 1];
    if (hi - lo <= 1) continue;
    std::uniform_int_distribution<int64_t> pick_cand(lo, hi - 1);
    const int64_t old = pick[i];
    int64_t next = pick_cand(rng);
    if (next == old) continue;
    pick[i] = next;
    const double trial = simulate(p, pick);
    // linear annealing beta0 -> beta1 (inverse temperature)
    const double beta =
        beta0 + (beta1 - beta0) * (double)step / (double)n_steps;
    if (trial <= cur ||
        unif(rng) < std::exp(-beta * (trial - cur))) {
      cur = trial;
      if (cur < best) { best = cur; best_pick = pick; }
    } else {
      pick[i] = old;
    }
  }
  std::memcpy(out_pick, best_pick.data(),
              sizeof(int64_t) * (size_t)n_mfcs);
  return best;
}

// Simulate a single explicit assignment (cost-model introspection).
double simulate_assignment(
    int n_mfcs, int n_devices,
    const int64_t* cand_offsets,
    const int32_t* cand_dev_lo, const int32_t* cand_dev_hi,
    const double* cand_time,
    const int32_t* roles, const int32_t* trainable,
    const int8_t* deps,
    const double* realloc_time, int64_t n_cands,
    const int64_t* pick) {
  Problem p{n_mfcs, n_devices, cand_offsets, cand_dev_lo, cand_dev_hi,
            cand_time, roles, trainable, deps, realloc_time, n_cands};
  std::vector<int64_t> v(pick, pick + n_mfcs);
  return simulate(p, v);
}

}  // extern "C"
